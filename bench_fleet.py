"""Fleet-scaling benchmark: aggregate RPS across router-fronted serving
replicas, plus the chaos kill drill.

Device work is MODELED WITH A SLEEP — the ``serving.predict`` failpoint
(armed ``delay:SECS``) fires inside the predictor lock, so each replica
behaves like one device that serves requests serially at a fixed
service time while the GIL stays free.  On the 2-vCPU bench host that
is the honest cost model: real per-replica accelerator time cannot be
reproduced with CPU threads, but its queueing behavior can.  What the
bench then measures is exactly the fleet capability: N replicas ≈ N
devices' worth of aggregate throughput behind one router, and a
hard-killed replica mid-load losing zero requests to failover.

    python bench_fleet.py --clients 8 --duration 3 --out BENCH_FLEET.json
"""

from __future__ import annotations

import argparse
import json
import tempfile
import threading
import time

import numpy as np


def build_model(dirname, feature_dim=4):
    """A minimal fc model: the compute is deliberately negligible — the
    armed ``serving.predict`` delay IS the device time."""
    import paddle_tpu as fluid
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[feature_dim])
        pred = layers.fc(input=x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe,
                                      main_program=main)
    return dirname


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]


def run_fleet(model_dir, n_replicas, clients, duration, service_ms,
              kill_mid_load=False, feature_dim=4):
    """One fleet run: master + N replicas + router, closed-loop clients
    for ``duration`` seconds; optionally hard-kill one replica mid-load
    via the ``fleet.replica.kill`` failpoint.  Returns a stats dict."""
    from paddle_tpu import profiler
    from paddle_tpu.fault import RetryPolicy, chaos
    from paddle_tpu.fleet import FleetReplica, FleetRouter
    from paddle_tpu.parallel.master import MasterServer, MasterService
    from paddle_tpu.serving import ServingClient

    profiler.runtime_metrics.reset()
    chaos.clear()
    # the device-time model: one serialized sleep per dispatch
    chaos.inject("serving.predict", delay=service_ms / 1000.0)
    svc = MasterService(replica_ttl=5.0)
    master = MasterServer(svc, port=0)
    master.start_background()
    maddr = f"{master.addr[0]}:{master.addr[1]}"
    replicas = [
        FleetReplica(model_dir, maddr, replica_id=f"r{i}",
                     lease_ttl=5.0, heartbeat_interval=0.25,
                     warmup=True, warmup_batch_sizes=(1,),
                     request_timeout=30.0).start()
        for i in range(n_replicas)]
    router = FleetRouter(master_addr=maddr, poll_interval=0.1)
    router.start_background()
    try:
        deadline = time.time() + 30
        while len(router.live_replicas()) < n_replicas and \
                time.time() < deadline:
            time.sleep(0.05)
        payload = {"x": np.random.RandomState(0)
                   .rand(1, feature_dim).astype("float32")}
        warm = ServingClient(router.addr)
        for _ in range(n_replicas * 2):  # touch every replica pre-clock
            warm.predict(payload)

        stats = [{"latencies": [], "failures": []}
                 for _ in range(clients)]

        def loop(out, stop_at):
            client = ServingClient(
                router.addr, deadline=30.0,
                retry=RetryPolicy(max_attempts=8, base_delay=0.05,
                                  max_delay=0.5, jitter="full"))
            while time.monotonic() < stop_at:
                t0 = time.perf_counter()
                try:
                    client.predict(payload)
                    out["latencies"].append(time.perf_counter() - t0)
                except Exception as e:       # a LOST request
                    out["failures"].append(repr(e))

        stop_at = time.monotonic() + duration
        threads = [threading.Thread(target=loop,
                                    args=(stats[i], stop_at))
                   for i in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        if kill_mid_load:
            time.sleep(duration * 0.4)
            chaos.inject("fleet.replica.kill", error=True, times=1)
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t_start
        lats = [x for s in stats for x in s["latencies"]]
        failures = [f for s in stats for f in s["failures"]]
        killed = [r.replica_id for r in replicas if r.killed]
        return {
            "replicas": n_replicas,
            "clients": clients,
            "requests_ok": len(lats),
            "failures": len(failures),
            "failure_samples": failures[:3],
            "elapsed_sec": elapsed,
            "rps": len(lats) / elapsed if elapsed > 0 else 0.0,
            "latency_ms": {
                "p50": (_percentile(lats, 50) or 0) * 1e3,
                "p99": (_percentile(lats, 99) or 0) * 1e3,
            },
            "failovers": profiler.runtime_metrics.counter(
                "fleet.failovers"),
            "retries": profiler.runtime_metrics.counter("fleet.retries"),
            "killed": killed,
        }
    finally:
        chaos.clear()
        for r in replicas:
            if not r.killed:
                r.drain()
        router.shutdown()
        master.shutdown()


def run_bench(clients=8, duration=2.5, service_ms=30.0, model_dir=None,
              scale_to=3):
    """1 replica vs ``scale_to`` replicas over the same router, then the
    kill drill at ``scale_to``; returns the JSON-ready summary."""
    own = model_dir is None
    if own:
        model_dir = build_model(
            tempfile.mkdtemp(prefix="ptfleet_") + "/model")
    kw = dict(clients=clients, duration=duration, service_ms=service_ms)
    one = run_fleet(model_dir, 1, **kw)
    many = run_fleet(model_dir, scale_to, **kw)
    drill = run_fleet(model_dir, scale_to, kill_mid_load=True, **kw)
    scaling = many["rps"] / one["rps"] if one["rps"] else None
    return {
        "clients": clients,
        "duration_sec": duration,
        "service_ms": service_ms,
        "fleet": {"1": one, str(scale_to): many},
        "scaling": scaling,
        "kill_drill": drill,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=2.5)
    ap.add_argument("--service-ms", type=float, default=30.0)
    ap.add_argument("--scale-to", type=int, default=3)
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(clients=args.clients, duration=args.duration,
                        service_ms=args.service_ms,
                        scale_to=args.scale_to)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("fleet", summary, args,
                                   "bench_fleet.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
