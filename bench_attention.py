"""Flash-attention crossover micro-bench (VERDICT r2 item 4; r4
methodology).

Times fwd+bwd of fused attention — Pallas flash kernels vs composed XLA
(``ops/attention_ops.py``) — at S in {256, 512, 1024, 2048, 4096}, bf16,
causal, B*S = 64k tokens, H=8, D=64 (transformer-base head shape).

Methodology (r4): DEVICE time per iteration, read from an xplane trace
of one jitted ``lax.scan`` of ITERS grad steps under ``jax.named_scope``
(``profiler.measure_device_seconds``) — tenant-proof on the shared chip
and free of the ~2.7 ms dispatch / ~100 ms sync wall-clock latencies
that inflated the r2/r3 absolute numbers (ratios were unaffected).

Writes ``BENCH_ATTENTION.md`` (the checked-in artifact the default
``PADDLE_TPU_FLASH_MIN_S`` cites) and prints one JSON line per S.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np


ITERS = 10
TOKENS = 1 << 16
HEADS, DIM = 8, 64
SEQS = (256, 512, 1024, 2048, 4096)


def time_path(use_pallas, S, B):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.attention_ops import fused_attention
    from paddle_tpu.profiler import measure_device_seconds

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, HEADS, S, DIM), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    k_mask = jnp.ones((B, S), jnp.bfloat16)
    scale = DIM ** -0.5
    scope = "attn_bench_iter"

    def loss(q, k, v):
        out = fused_attention(q, k, v, k_mask, True, scale, use_pallas)
        return jnp.sum(out.astype(jnp.float32))

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def many(q, k, v):
        def body(qq, _):
            # the carry dependency (qq + 0*g) chains the iterations so
            # XLA cannot elide them; the scope makes the device-time
            # read tenant-proof on the shared chip
            with jax.named_scope(scope):
                g = grad(qq, k, v)
            return qq + 0.0 * g[0], g[0][0, 0, 0, 0]
        _, ys = jax.lax.scan(body, q, jnp.arange(ITERS, dtype=jnp.int32))
        return ys[-1]

    np.asarray(many(q, k, v))  # compile + settle
    trials = []
    for _ in range(int(os.environ.get("PADDLE_TPU_BENCH_TRIALS", "3"))):
        dev_s = measure_device_seconds(
            lambda: np.asarray(many(q, k, v)), scope=scope)
        trials.append(dev_s / ITERS)
    return float(np.median(trials)), trials


def main():
    rows = []
    for S in SEQS:
        B = max(1, TOKENS // S)

        def timed(use_pallas):
            try:
                per_iter, trials = time_path(use_pallas, S, B)
                return per_iter * 1e3, [t * 1e3 for t in trials]
            except Exception as e:  # XLA path OOMs once [B,H,S,S] f32
                if "RESOURCE_EXHAUSTED" in str(e) or "memory" in \
                        str(e).lower():
                    return None, []
                raise

        flash_ms, flash_tr = timed(True)
        xla_ms, xla_tr = timed(False)
        row = {"S": S, "B": B,
               "flash_ms": round(flash_ms, 3) if flash_ms else None,
               "xla_ms": round(xla_ms, 3) if xla_ms else None,
               "speedup": round(xla_ms / flash_ms, 3)
               if flash_ms and xla_ms else None}
        rows.append(row)
        print(json.dumps(row))
        print(f"#   flash trials {['%.2f' % t for t in flash_tr]} "
              f"xla trials {['%.2f' % t for t in xla_tr]}",
              file=sys.stderr)

    crossover = next(
        (r["S"] for r in rows
         if r["flash_ms"] and (r["xla_ms"] is None
                               or r["speedup"] > 1.0)), None)
    lines = [
        "# Flash-attention crossover (measured)",
        "",
        f"Chip: {_device_kind()}; fwd+bwd, causal, bf16, "
        f"B*S = {TOKENS} tokens, H={HEADS}, D={DIM}; per-iter DEVICE "
        f"time (xplane, named-scope, median of trials — "
        f"see bench_attention.py r4 methodology).",
        "",
        "| S | B | flash ms/iter | XLA ms/iter | speedup |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        xla = r["xla_ms"] if r["xla_ms"] is not None else "OOM"
        sp = f"{r['speedup']}x" if r["speedup"] is not None else "inf"
        lines.append(f"| {r['S']} | {r['B']} | {r['flash_ms']} | "
                     f"{xla} | {sp} |")
    lines += [
        "",
        f"Measured ISOLATED-kernel crossover: flash wins from "
        f"**S = {crossover}** (speedup > 1, or the composed path's "
        f"[B,H,S,S] f32 scores no longer fit HBM).",
        "",
        "This DEVICE-time crossover agrees with the in-model evidence "
        "(bench A/B + per-op profile, r4): the gate "
        "(`PADDLE_TPU_FLASH_MIN_S`, models/transformer.py) defaults to "
        "512.  At S=256 the composed path wins both isolated (QK^T at "
        "D=64 half-fills the MXU while the [S,S] score round-trip is "
        "cheap) and in-model, where the pallas custom call additionally "
        "pins a [B,H,S,D] layout (~15ms/step of HBM transposes XLA "
        "otherwise folds into the projection matmuls) and splits fusion "
        "clusters (~11ms).  Earlier wall-clock versions of this bench "
        "showed a fake S=256 flash win — dispatch/sync overhead "
        "distorted sub-5ms kernels.",
    ]
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_ATTENTION.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"# crossover S={crossover}", file=sys.stderr)


def _device_kind():
    import jax
    return getattr(jax.devices()[0], "device_kind", "unknown")


if __name__ == "__main__":
    main()
