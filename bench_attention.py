"""Flash-attention crossover micro-bench (VERDICT r2 item 4).

Times fwd+bwd of fused attention — Pallas flash kernels vs composed XLA
(``ops/attention_ops.py``) — at S in {256, 512, 1024, 2048, 4096}, bf16,
causal, B*S = 64k tokens, H=8, D=64 (transformer-base head shape).

Methodology: each timed sample queues ``ITERS`` chained grad steps and
syncs once (device-queue pipelining amortizes the axon per-dispatch
latency); the reported per-iter time is the median of
``PADDLE_TPU_BENCH_TRIALS`` (default 3 here) samples via
``bench.measure_trials``.

Writes ``BENCH_ATTENTION.md`` (the checked-in artifact the default
``PADDLE_TPU_FLASH_MIN_S`` cites) and prints one JSON line per S.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from bench import measure_trials

ITERS = 10
TOKENS = 1 << 16
HEADS, DIM = 8, 64
SEQS = (256, 512, 1024, 2048, 4096)


def time_path(use_pallas, S, B):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.attention_ops import fused_attention

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, HEADS, S, DIM), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    k_mask = jnp.ones((B, S), jnp.bfloat16)
    scale = DIM ** -0.5

    def loss(q, k, v):
        out = fused_attention(q, k, v, k_mask, True, scale, use_pallas)
        return jnp.sum(out.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    dq, _, _ = step(q, k, v)
    np.asarray(dq[0, 0, 0, 0])  # compile + settle

    def run_once():
        nonlocal q
        last = None
        qq = q
        for _ in range(ITERS):
            g = step(qq, k, v)
            # chain a dependency so iterations cannot be elided, while
            # keeping the workload identical
            qq = qq + 0.0 * g[0]
            last = g
        np.asarray(last[0][0, 0, 0, 0])  # one sync for the whole queue

    dt, trials = measure_trials(run_once,
                                n_trials=int(os.environ.get(
                                    "PADDLE_TPU_BENCH_TRIALS", "3")))
    return dt / ITERS, [t / ITERS for t in trials]


def main():
    rows = []
    for S in SEQS:
        B = max(1, TOKENS // S)

        def timed(use_pallas):
            try:
                per_iter, trials = time_path(use_pallas, S, B)
                return per_iter * 1e3, [t * 1e3 for t in trials]
            except Exception as e:  # XLA path OOMs once [B,H,S,S] f32
                if "RESOURCE_EXHAUSTED" in str(e) or "memory" in \
                        str(e).lower():
                    return None, []
                raise

        flash_ms, flash_tr = timed(True)
        xla_ms, xla_tr = timed(False)
        row = {"S": S, "B": B,
               "flash_ms": round(flash_ms, 3) if flash_ms else None,
               "xla_ms": round(xla_ms, 3) if xla_ms else None,
               "speedup": round(xla_ms / flash_ms, 3)
               if flash_ms and xla_ms else None}
        rows.append(row)
        print(json.dumps(row))
        print(f"#   flash trials {['%.2f' % t for t in flash_tr]} "
              f"xla trials {['%.2f' % t for t in xla_tr]}",
              file=sys.stderr)

    crossover = next(
        (r["S"] for r in rows
         if r["flash_ms"] and (r["xla_ms"] is None
                               or r["speedup"] > 1.0)), None)
    lines = [
        "# Flash-attention crossover (measured)",
        "",
        f"Chip: {_device_kind()}; fwd+bwd, causal, bf16, "
        f"B*S = {TOKENS} tokens, H={HEADS}, D={DIM}; per-iter median "
        f"of queued-{ITERS} samples (see bench_attention.py).",
        "",
        "| S | B | flash ms/iter | XLA ms/iter | speedup |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        xla = r["xla_ms"] if r["xla_ms"] is not None else "OOM"
        sp = f"{r['speedup']}x" if r["speedup"] is not None else "inf"
        lines.append(f"| {r['S']} | {r['B']} | {r['flash_ms']} | "
                     f"{xla} | {sp} |")
    lines += [
        "",
        f"Measured ISOLATED-kernel crossover: flash wins from "
        f"**S = {crossover}** (speedup > 1, or the composed path's "
        f"[B,H,S,S] f32 scores no longer fit HBM).",
        "",
        "IN-MODEL the gate (`PADDLE_TPU_FLASH_MIN_S`, "
        "models/transformer.py) defaults to 512: at S=256 the bench "
        "A/B + per-op profile (r4) show the composed path still wins "
        "inside the transformer step — the pallas custom call pins a "
        "[B,H,S,D] layout costing ~15ms/step of HBM transposes that "
        "XLA otherwise folds into the projection matmuls, and the "
        "call boundary splits fusion clusters (~11ms) — more than the "
        "kernel's isolated advantage at D=64.",
    ]
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_ATTENTION.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"# crossover S={crossover}", file=sys.stderr)


def _device_kind():
    import jax
    return getattr(jax.devices()[0], "device_kind", "unknown")


if __name__ == "__main__":
    main()
