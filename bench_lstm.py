"""Stacked dynamic-LSTM text-classification benchmark — the reference's
RNN anchor (``benchmark/README.md:112-118``: 2xLSTM+fc, IMDB, dict 30k,
seq len 100, batch 64; K40m: 83 / 184 / 641 ms/batch at hidden
256 / 512 / 1280) on one TPU chip, through the BUCKETED dynamic-LoD
path (lod.py) — the distinctive ragged-tensor workload this framework
carries a LoD subsystem for.

Methodology (see BENCH_LSTM.md): every batch has fresh random lengths
(2..100); a WINDOW of ``WINDOW`` batches pads to one bucket signature
and runs as ONE ``run_steps`` device dispatch (the executor's streaming
ragged mode, r5) — on this container the axon tunnel costs ~100 ms per
dispatch+sync round trip, so per-batch ``run()`` walls measure the
tunnel, not the framework (measured: 132 ms wall vs 5.9 ms device at
hidden 256).  Wall per batch is reported over the window; the
bucketed-vs-exact-static masking tax is measured in tenant-proof DEVICE
time (profiler.scope_device_seconds) since the static path must run
per-batch.

Prints one JSON line (driver convention) for hidden=512 — the middle
anchor — and the other operating points to stderr:
  {"metric": "stacked_lstm_ms_per_batch_h512", ...,
   "vs_baseline": K40m_ms / our_ms}

Model config mirrors ``benchmark/fluid/stacked_dynamic_lstm.py``
(emb 512, Adam) with the README table's 2-layer stack; peepholes on
(the README calls out peephole lstmemory).
"""

from __future__ import annotations

import json
import sys

import numpy as np

DICT, EMB, LAYERS, BATCH, SEQ = 30000, 512, 2, 64, 100
WINDOW = 16
K40M_MS = {256: 83.0, 512: 184.0, 1280: 641.0}


def _ragged_batches(n, seed):
    from paddle_tpu.models.stacked_lstm import fake_batch
    return [fake_batch(BATCH, SEQ, DICT, seed=seed + i) for i in range(n)]


def _build(hidden, bucketed):
    import paddle_tpu as fluid
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_cost, acc, _ = stacked_lstm_net(
            DICT, emb_dim=EMB, hidden_dim=hidden, n_layers=LAYERS)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    main.lod_buckets = bucketed
    return main, startup, avg_cost


def bench_dynamic(hidden, n_windows=4):
    """Bucketed streaming: wall ms/batch over run_steps windows, plus
    one traced window's device ms/batch."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import profiler
    import bench

    main, startup, avg_cost = _build(hidden, bucketed=True)
    windows = [_ragged_batches(WINDOW, seed=100 * w)
               for w in range(n_windows)]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)

        def feed_of(w):
            return {
                "words": [b["words"] for b in windows[w]],
                "label": np.stack([b["label"] for b in windows[w]]),
            }

        for w in range(n_windows):       # compile every window signature
            exe.run_steps(main, feed=feed_of(w),
                          fetch_list=[avg_cost.name], steps=WINDOW)
        k = [0]

        def run_once():
            exe.run_steps(main, feed=feed_of(k[0] % n_windows),
                          fetch_list=[avg_cost.name], steps=WINDOW)
            k[0] += 1

        dt, _ = bench.measure_trials(run_once, n_trials=5)
        dev_s = profiler.measure_device_seconds(run_once, scope="ptop_")
    return dt * 1e3 / WINDOW, dev_s * 1e3 / WINDOW


def bench_static_device(hidden, n_meas=6):
    """Exact static LoD (all sequences SEQ tokens, one compile):
    tenant-proof device ms/batch — the masking-tax reference point."""
    import paddle_tpu as fluid
    from paddle_tpu import profiler

    main, startup, avg_cost = _build(hidden, bucketed=False)
    rng = np.random.RandomState(11)
    splits = [int(s) for s in np.arange(BATCH + 1) * SEQ]
    feeds = [{
        "words": (rng.randint(0, DICT, (BATCH * SEQ, 1)).astype("int64"),
                  [splits]),
        "label": rng.randint(0, 2, (BATCH, 1)).astype("int64"),
    } for _ in range(n_meas)]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for b in feeds[:2]:
            exe.run(main, feed=b, fetch_list=[avg_cost.name])

        def run_all():
            for b in feeds:
                exe.run(main, feed=b, fetch_list=[avg_cost.name])

        dev_s = profiler.measure_device_seconds(run_all, scope="ptop_")
    return dev_s * 1e3 / n_meas


def main():
    import os
    import jax
    global DICT, EMB, BATCH, SEQ, WINDOW
    hiddens = tuple(int(h) for h in os.environ.get(
        "PADDLE_TPU_LSTM_HIDDENS", "256,512,1280").split(","))
    if not any(d.platform != "cpu" for d in jax.devices()):
        DICT, EMB, BATCH, SEQ, WINDOW = 1000, 32, 8, 12, 4
        hiddens = (32,)
    for hidden in hiddens:
        dyn_ms, dyn_dev = bench_dynamic(hidden)
        static_dev = bench_static_device(hidden)
        base = K40M_MS.get(hidden)
        line = {
            "metric": f"stacked_lstm_ms_per_batch_h{hidden}",
            "value": round(dyn_ms, 3), "unit": "ms/batch",
            "vs_baseline": round(base / dyn_ms, 2) if base else None,
            "device_ms": round(dyn_dev, 3),
            "static_device_ms": round(static_dev, 3),
            "masking_tax": round(dyn_dev / static_dev, 3)
            if static_dev else None,
        }
        print(json.dumps(line),
              file=sys.stdout if hidden == 512 else sys.stderr)


if __name__ == "__main__":
    main()
