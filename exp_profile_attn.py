"""Profile one training step of the flagship bench model, flash vs
composed, attributing device time per IR op (round-4 S=256 analysis)."""
import os, sys, tempfile
os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
import numpy as np
import jax

mode = sys.argv[1] if len(sys.argv) > 1 else "flash"
os.environ["PADDLE_TPU_FLASH_MIN_S"] = "256" if mode == "flash" else "99999"

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T
from paddle_tpu import profiler

hp = T.ModelHyperParams()
batch, seq, steps = 256, 256, 4
main_prog, startup = fluid.Program(), fluid.Program()
batches = [T.fake_batch(batch, seq, seq, hp, seed=s) for s in range(steps)]
with fluid.program_guard(main_prog, startup):
    avg_cost, _ = T.transformer(batch, seq, seq, hp)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
main_prog.amp = True
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
               for k in batches[0]}
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=steps)  # warmup/compile
    td = tempfile.mkdtemp()
    jax.profiler.start_trace(td)
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=steps)
    jax.profiler.stop_trace()
    table, rows = profiler.compiled_op_table(td)
    total = sum(r[2] for r in rows)
    print(f"mode={mode} total_device_s={total:.4f} ({steps} steps)")
    for op, calls, sec in rows[:25]:
        print(f"  {op:40s} {calls:6d} {sec*1e3/steps:9.3f} ms/step")
