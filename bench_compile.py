"""Cold-start bench: trace+compile wall-time across the zoo, with the
Program-IR optimization pipeline off vs on (``PADDLE_TPU_OPT``).

The persistent XLA compile cache (PR 2) only AMORTIZES cold-start cost;
the ``analysis/opt`` pipeline SHRINKS it — fewer traced ops (DCE of
unfetched autodiff chains, CSE, constant folding, elementwise fusion)
and a statically proven RNG-key plan that drops the per-op
``fold_in`` threefry chains from the jaxpr.  This bench measures what
that buys: per zoo model, the summed trace+lower+backend phase times of
a COLD process's first step (captured by ``obs.perf.instrument_jit``),
plus the steady-state step time (which must not regress — the passes
may only remove work XLA would have DCE'd anyway).

Each measurement runs in its own subprocess (fresh jax, fresh caches —
in-process A/B flatters whichever side compiles second), alternating
baseline/optimized order across trials, taking the per-side minimum.

    python bench_compile.py --out BENCH_COMPILE.json
    python bench_compile.py --smoke        # fast CI schema check
    python bench_compile.py --record-trajectory default

Headline metrics (recorded per ``--record-trajectory``, guarded by
``paddle_tpu bench check``): ``reduction_second_best`` — the
second-best per-model trace+compile reduction, i.e. "at least two zoo
models improve by this much" (the ISSUE-15 acceptance floor is 0.15) —
and ``step_time_ratio_worst`` (optimized/baseline steady step, must
stay ~1).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

DEFAULT_MODELS = ("mnist", "transformer", "gen_lm")
FULL_MODELS = ("mnist", "transformer", "gen_lm", "resnet", "vgg")

WORKER = r'''
import json, os, sys, time, warnings
warnings.filterwarnings("ignore")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import paddle_tpu as fluid
from paddle_tpu.models import build_train_program, synth_feed
from paddle_tpu.obs import perf

name = sys.argv[1]
steady_iters = int(sys.argv[2])

main, startup, feeds, fetches = build_train_program(name)
main.random_seed = startup.random_seed = 11
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    feed = synth_feed(main, feeds)
    # the cold start a fresh process pays: startup compile+run plus the
    # first step's trace/lower/backend (optimization time included on
    # the PADDLE_TPU_OPT=1 side — the pipeline must pay for itself)
    t0 = time.perf_counter()
    exe.run(startup)
    exe.run(main, feed=feed, fetch_list=fetches, scope=scope)
    cold_wall = time.perf_counter() - t0
    phases = sum(sum(r["phases"].values()) for r in perf.records())
    steady = []
    for _ in range(steady_iters):
        t0 = time.perf_counter()
        exe.run(main, feed=feed, fetch_list=fetches, scope=scope)
        steady.append(time.perf_counter() - t0)
    opt_report = None
    for prog in exe._opt_cache.values():
        r = getattr(prog, "_opt_report", None)
        if r is not None and not getattr(prog, "_opt_interpret", False):
            opt_report = r.to_dict()
print(json.dumps({
    "cold_start_seconds": cold_wall,
    "trace_compile_seconds": phases,
    "steady_step_seconds": min(steady) if steady else None,
    "opt": opt_report,
}))
'''


def _measure(model, opt, steady_iters):
    env = dict(os.environ)
    env["PADDLE_TPU_OPT"] = "1" if opt else "0"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PADDLE_TPU_COMPILE_CACHE", None)  # cold means cold
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(WORKER)
        path = f.name
    try:
        out = subprocess.run(
            [sys.executable, path, model, str(steady_iters)],
            env=env, capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(
                f"bench worker failed for {model} (opt={opt}):\n"
                f"{out.stderr[-2000:]}")
        return json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def run_bench(models=DEFAULT_MODELS, trials=3, steady_iters=4,
              smoke=False):
    if smoke:
        models, trials, steady_iters = ("mnist",), 1, 2
    results = {}
    for model in models:
        base_runs, opt_runs = [], []
        for t in range(trials):
            # alternate order so ambient load biases neither side
            order = ((False, True) if t % 2 == 0 else (True, False))
            for opt in order:
                (opt_runs if opt else base_runs).append(
                    _measure(model, opt, steady_iters))
        base = min(r["cold_start_seconds"] for r in base_runs)
        opt = min(r["cold_start_seconds"] for r in opt_runs)
        pbase = min(r["trace_compile_seconds"] for r in base_runs)
        popt = min(r["trace_compile_seconds"] for r in opt_runs)
        sbase = min(r["steady_step_seconds"] for r in base_runs)
        sopt = min(r["steady_step_seconds"] for r in opt_runs)
        results[model] = {
            "cold_start_seconds": {"baseline": base, "optimized": opt},
            "captured_phase_seconds": {"baseline": pbase,
                                       "optimized": popt},
            "reduction": 1.0 - opt / base if base > 0 else 0.0,
            "steady_step_ms": {"baseline": sbase * 1e3,
                               "optimized": sopt * 1e3},
            "step_time_ratio": sopt / sbase if sbase > 0 else 1.0,
            "opt_report": opt_runs[-1].get("opt"),
        }
    reductions = sorted((r["reduction"] for r in results.values()),
                        reverse=True)
    summary = {
        "bench": "compile",
        "smoke": bool(smoke),
        "models": results,
        "reduction_best": reductions[0],
        "reduction_second_best":
            reductions[1] if len(reductions) > 1 else reductions[0],
        "models_ge_15pct": sum(1 for r in reductions if r >= 0.15),
        "step_time_ratio_worst": max(r["step_time_ratio"]
                                     for r in results.values()),
    }
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--models", default=None,
                    help="comma list (default: mnist,transformer,gen_lm)")
    ap.add_argument("--full", action="store_true",
                    help="bench the larger zoo set too (resnet, vgg)")
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--steady-iters", type=int, default=4)
    ap.add_argument("--smoke", action="store_true",
                    help="1 trial, mnist only — CI schema check")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the JSON summary here")
    from paddle_tpu.obs.bench_history import (add_record_args,
                                              record_from_args)
    add_record_args(ap)
    args = ap.parse_args(argv)
    models = DEFAULT_MODELS
    if args.full:
        models = FULL_MODELS
    if args.models:
        models = tuple(s for s in args.models.split(",") if s)
    summary = run_bench(models=models, trials=args.trials,
                        steady_iters=args.steady_iters, smoke=args.smoke)
    print(json.dumps(summary, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")
    record_from_args("compile", summary, args, "bench_compile.py")
    ok = summary["reduction_second_best"] >= 0.15 and \
        summary["step_time_ratio_worst"] <= 1.10
    if not args.smoke and not ok:
        print("bench_compile: acceptance floor missed "
              "(>=15% reduction on >=2 models, steady step no worse)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
