"""Experiment: Pallas direct conv for ResNet's dominant shapes (round-4
VERDICT item 1 — ResNet-50 MFU 0.239 vs >=0.45 north star).

Formulation: shift-and-accumulate NHWC — a 3x3 stride-1 same-pad conv is
nine shifted [M, Ci] @ [Ci, Co] matmuls accumulated in a VMEM f32
accumulator (no im2col patch materialization; x block loaded ONCE for
all nine taps), with the BN scale/bias + ReLU fused into the epilogue.
Grid over batch; each program holds the whole [H, W, C] image in VMEM
(ResNet's post-stem feature maps are small: 56x56x64 = 392KB bf16 down
to 7x7x512 = 49KB).

Benchmarks fwd per shape against jax.lax.conv_general_dilated in NCHW
and NHWC (bf16, preferred f32) and prints achieved TFLOP/s per variant.
"""

from __future__ import annotations

import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bench import measure_trials

ITERS = 20
BATCH = 256

# (H, C_in, C_out) for the stage-2..5 3x3 bodies of ResNet-50
SHAPES_3X3 = [(56, 64, 64), (28, 128, 128), (14, 256, 256), (7, 512, 512)]
# the 1x1 expand convs (pure matmuls — XLA's own efficiency reference)
SHAPES_1X1 = [(56, 64, 256), (14, 256, 1024)]


def _conv3x3_kernel(x_ref, w_ref, scale_ref, bias_ref, o_ref, *, H, W,
                    C, Co, NB, relu):
    """NB images [NB, H, W, C] -> [NB, H, W, Co]; w [9, C, Co] laid out
    tap-major; scale/bias [1, Co] BN-folded epilogue."""
    for b in range(NB):
        # pad once to [H+2, W+2, C]; each tap is then a static slice
        xp = jnp.pad(x_ref[b], ((1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros((H * W, Co), jnp.float32)
        for ky in range(3):
            for kx in range(3):
                shifted = jax.lax.slice(
                    xp, (ky, kx, 0), (ky + H, kx + W, C))
                acc += jax.lax.dot_general(
                    shifted.reshape(H * W, C), w_ref[ky * 3 + kx],
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        out = acc * scale_ref[0][None, :] + bias_ref[0][None, :]
        if relu:
            out = jnp.maximum(out, 0.0)
        o_ref[b] = out.reshape(H, W, Co).astype(o_ref.dtype)


def pallas_conv3x3(x, w, scale, bias, relu=True, nb=1):
    """x [N, H, W, C] bf16; w [3, 3, C, Co]; BN-folded scale/bias [Co]."""
    N, H, W, C = x.shape
    Co = w.shape[3]
    w9 = w.reshape(9, C, Co)
    return pl.pallas_call(
        functools.partial(_conv3x3_kernel, H=H, W=W, C=C, Co=Co, NB=nb,
                          relu=relu),
        grid=(N // nb,),
        in_specs=[
            pl.BlockSpec((nb, H, W, C), lambda n: (n, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((9, C, Co), lambda n: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Co), lambda n: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Co), lambda n: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((nb, H, W, Co), lambda n: (n, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, H, W, Co), x.dtype),
    )(x, w9, scale.reshape(1, -1), bias.reshape(1, -1))


def check_numerics():
    H, C, Co = 14, 64, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (2, H, H, C),
                          jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, C, Co),
                          jnp.float32) * 0.1
    scale = jnp.ones((Co,))
    bias = jnp.zeros((Co,))
    got = pallas_conv3x3(x, w, scale, bias, relu=False)
    want = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    err = float(jnp.max(jnp.abs(got - want)))
    print(f"# numerics 3x3 maxerr={err:.5f}", file=sys.stderr)
    assert err < 1e-2


_SCOPE = "measured_op"


def bench(fn, x, *rest):
    """Profile-based timing: wall clocks on this backend are poisoned by
    ~2.7ms dispatch and ~100ms sync latencies, so run the op ITERS times
    inside one jitted scan under a named_scope and read the actual device
    time off the xplane trace (profiler.scope_device_seconds)."""
    from paddle_tpu.profiler import measure_device_seconds

    @jax.jit
    def many(x, *rest):
        def body(carry, i):
            with jax.named_scope(_SCOPE):
                out = fn((x + i.astype(x.dtype)), *rest)
            return carry + out.ravel()[0].astype(jnp.float32), None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    np.asarray(many(x, *rest))  # compile + settle
    td = tempfile.mkdtemp()
    jax.profiler.start_trace(td)
    np.asarray(many(x, *rest))
    jax.profiler.stop_trace()

    total = scope_device_seconds(td, _SCOPE)
    import shutil
    shutil.rmtree(td, ignore_errors=True)
    if total == 0:
        raise RuntimeError("no device events matched the scope")
    return total / ITERS


def main():
    check_numerics()
    for H, C, Co in SHAPES_3X3:
        flops = 2 * BATCH * H * H * C * Co * 9
        x_nhwc = jax.random.normal(jax.random.PRNGKey(0),
                                   (BATCH, H, H, C), jnp.bfloat16)
        x_nchw = jnp.transpose(x_nhwc, (0, 3, 1, 2))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, C, Co),
                              jnp.bfloat16) * 0.05
        w_oihw = jnp.transpose(w, (3, 2, 0, 1))
        scale = jnp.ones((Co,), jnp.float32)
        bias = jnp.zeros((Co,), jnp.float32)
        row = {"shape": f"{H}x{H}x{C}->{Co} 3x3"}

        t = bench(lambda x, w: jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16),
            x_nchw, w_oihw)
        row["xla_nchw_tflops"] = round(flops / t / 1e12, 2)

        t = bench(lambda x, w: jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16),
            x_nhwc, w)
        row["xla_nhwc_tflops"] = round(flops / t / 1e12, 2)

        for nb in (1, 2, 4):
            try:
                t = bench(functools.partial(
                    pallas_conv3x3, relu=True, nb=nb),
                    x_nhwc, w, scale, bias)
                row[f"pallas_nb{nb}_tflops"] = round(flops / t / 1e12, 2)
            except Exception as e:
                row[f"pallas_nb{nb}_tflops"] = f"ERR {type(e).__name__}"
                print(f"# {row['shape']} nb={nb}: {str(e)[:200]}",
                      file=sys.stderr)
        print(json.dumps(row))
        sys.stdout.flush()

    for H, C, Co in SHAPES_1X1:
        flops = 2 * BATCH * H * H * C * Co
        x = jax.random.normal(jax.random.PRNGKey(0), (BATCH, H, H, C),
                              jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (C, Co),
                              jnp.bfloat16) * 0.05
        t = bench(lambda x, w: jax.lax.dot_general(
            x.reshape(-1, C), w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16),
            x, w)
        print(json.dumps({"shape": f"{H}x{H}x{C}->{Co} 1x1",
                          "matmul_tflops": round(flops / t / 1e12, 2)}))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
