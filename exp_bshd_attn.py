"""Experiment: BSHD-layout small-S flash attention (round-4, after the
profile showed the BHSD flash path pays 15ms/step of HBM transposes that
the composed path fuses away).

Kernels take q/k/v in the model's natural [B, S, H, D] layout (one
reshape away from the [B, S, H*D] projection output — free), grid over
B, heads looped inside the kernel after an in-VMEM swapaxes relayout.
Outputs (ctx and grads) come back in BSHD too, so the surrounding
program has NO transposes at all.

Times fwd+bwd at the flagship shape (B=256, H=8, S=256, D=64, causal,
bf16) against composed XLA (with its fused transposes measured inside a
mini 1-layer model) and checks numerics vs the reference path.
"""

from __future__ import annotations

import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bench import measure_trials
from paddle_tpu.ops.attention_ops import _reference_attention, NEG_INF

ITERS = 10
B, H, S, D = 256, 8, 256, 64


def _causal_bias(S):
    row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    return jnp.where(col > row, NEG_INF, 0.0)


def _bshd_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, res_ref, *,
                     causal, scale, H, S):
    bias = _causal_bias(S) if causal else None
    q = jnp.swapaxes(q_ref[0], 0, 1)      # [H, S, D] relayout in VMEM
    k = jnp.swapaxes(k_ref[0], 0, 1)
    v = jnp.swapaxes(v_ref[0], 0, 1)
    mask = mask_ref[0][:, 0]              # [S]
    for h in range(H):
        s = jax.lax.dot_general(
            q[h], k[h], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = s + (1.0 - mask.astype(jnp.float32))[None, :] * NEG_INF
        if bias is not None:
            s = s + bias
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v[h],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # store per head immediately: keeps one head's temporaries live
        # at a time (stacking all heads blows the 16MB scoped VMEM)
        o_ref[0, :, h, :] = (o / l).astype(o_ref.dtype)
        res_ref[0, :, h, :] = jnp.concatenate([m, jnp.log(l)], axis=1)


def _bshd_bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, res_ref,
                     delta_ref, dq_ref, dk_ref, dv_ref, *, causal, scale,
                     H, S):
    bias = _causal_bias(S) if causal else None
    q = jnp.swapaxes(q_ref[0], 0, 1)
    k = jnp.swapaxes(k_ref[0], 0, 1)
    v = jnp.swapaxes(v_ref[0], 0, 1)
    do = jnp.swapaxes(do_ref[0], 0, 1)
    res = jnp.swapaxes(res_ref[0], 0, 1)     # [H, S, 2]
    delta = jnp.swapaxes(delta_ref[0], 0, 1)  # [H, S, 1]
    mask = mask_ref[0][:, 0]
    for h in range(H):
        s = jax.lax.dot_general(
            q[h], k[h], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = s + (1.0 - mask.astype(jnp.float32))[None, :] * NEG_INF
        if bias is not None:
            s = s + bias
        m = res[h][:, 0:1]
        logl = res[h][:, 1:2]
        p = jnp.exp((s - m) - logl)
        dv_ref[0, :, h, :] = jax.lax.dot_general(
            p.astype(do.dtype), do[h],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do[h], v[h], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[h]) * scale
        dq_ref[0, :, h, :] = jax.lax.dot_general(
            ds.astype(k.dtype), k[h],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[0, :, h, :] = jax.lax.dot_general(
            ds.astype(q.dtype), q[h],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def bshd_fwd(q, k, v, k_mask, causal, scale):
    B, S, H, D = q.shape

    def spec(h, w):
        return pl.BlockSpec((1, S, h, w), lambda b: (b, 0, 0, 0),
                            memory_space=pltpu.VMEM)

    mspec = pl.BlockSpec((1, S, 1), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM)
    out, res = pl.pallas_call(
        functools.partial(_bshd_fwd_kernel, causal=causal, scale=scale,
                          H=H, S=S),
        grid=(B,),
        in_specs=[spec(H, D), spec(H, D), spec(H, D), mspec],
        out_specs=[spec(H, D), spec(H, 2)],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
            jax.ShapeDtypeStruct((B, S, H, 2), jnp.float32),
        ],
    )(q, k, v, k_mask[:, :, None])
    return out, res


def bshd_bwd(q, k, v, k_mask, o, res, g, causal, scale):
    B, S, H, D = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)   # [B, S, H, 1]

    def spec(h, w):
        return pl.BlockSpec((1, S, h, w), lambda b: (b, 0, 0, 0),
                            memory_space=pltpu.VMEM)

    mspec = pl.BlockSpec((1, S, 1), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bshd_bwd_kernel, causal=causal, scale=scale,
                          H=H, S=S),
        grid=(B,),
        in_specs=[spec(H, D), spec(H, D), spec(H, D), mspec, spec(H, D),
                  spec(H, 2), spec(H, 1)],
        out_specs=[spec(H, D), spec(H, D), spec(H, D)],
        out_shape=[jax.ShapeDtypeStruct((B, S, H, D), q.dtype)] * 3,
    )(q, k, v, k_mask[:, :, None], g, res, delta)
    return dq, dk, dv


def make_bshd_attention():
    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
    def attn(q, k, v, k_mask, causal, scale):
        out, _ = fwd(q, k, v, k_mask, causal, scale)
        return out

    def fwd(q, k, v, k_mask, causal, scale):
        out, res = bshd_fwd(q, k, v, k_mask, causal, scale)
        return out, (q, k, v, k_mask, out, res)

    def bwd(causal, scale, resids, g):
        q, k, v, k_mask, o, res = resids
        return bshd_bwd(q, k, v, k_mask, o, res, g, causal, scale) + (None,)

    attn.defvjp(fwd, bwd)
    return attn


def check_numerics():
    b, s = 4, 256
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    k_mask = (jax.random.uniform(jax.random.PRNGKey(3), (b, s))
              > 0.1).astype(jnp.bfloat16)
    scale = D ** -0.5
    attn = make_bshd_attention()
    to_bhsd = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    for causal in (False, True):
        out = attn(q, k, v, k_mask, causal, scale)
        ref = _reference_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                                   k_mask, causal, scale)
        err = float(jnp.max(jnp.abs(to_bhsd(out).astype(jnp.float32)
                                    - ref.astype(jnp.float32))))

        def loss_b(q, k, v):
            return jnp.sum(attn(q, k, v, k_mask, causal, scale)
                           .astype(jnp.float32) * jnp.arange(D))

        def loss_r(q, k, v):
            return jnp.sum(_reference_attention(
                to_bhsd(q), to_bhsd(k), to_bhsd(v), k_mask, causal,
                scale).astype(jnp.float32)
                * jnp.arange(D))

        gb = jax.jit(jax.grad(loss_b, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))(q, k, v)
        gerr = max(float(jnp.max(jnp.abs(
            a.astype(jnp.float32) - to_bhsd(b_).astype(jnp.float32))))
            for a, b_ in zip(gb, gr))
        print(f"# numerics causal={causal}: fwd maxerr={err:.4f} "
              f"bwd maxerr={gerr:.4f}", file=sys.stderr)
        assert err < 0.1 and gerr < 0.5, "numerics mismatch"


def main():
    check_numerics()
    scale = D ** -0.5
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    k_mask = jnp.ones((B, S), jnp.bfloat16)
    attn = make_bshd_attention()

    def time_step(step):
        g = step(q, k, v)
        np.asarray(g[0][0, 0, 0, 0])

        def run_once():
            qq = q
            last = None
            for _ in range(ITERS):
                gg = step(qq, k, v)
                qq = qq + 0.0 * gg[0]
                last = gg
            np.asarray(last[0][0, 0, 0, 0])

        dt, _ = measure_trials(run_once, n_trials=3)
        return dt / ITERS * 1e3

    def mk(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    row = {"B": B, "S": S}
    row["bshd_ms"] = round(time_step(mk(
        lambda q, k, v: attn(q, k, v, k_mask, True, scale))), 3)

    # composed WITH its transposes, as the model would run it
    def composed(q, k, v):
        tb = lambda x: jnp.transpose(x, (0, 2, 1, 3))
        out = _reference_attention(tb(q), tb(k), tb(v), k_mask, True,
                                   scale)
        return jnp.transpose(out, (0, 2, 1, 3))

    row["xla_bshd_ms"] = round(time_step(mk(composed)), 3)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
