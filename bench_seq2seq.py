"""Attention seq2seq (NMT) benchmark — the reference's
``benchmark/fluid/machine_translation.py`` workload (bi-LSTM encoder +
DynamicRNN decoder with additive attention; emb/enc/dec 512, dict 30k,
batch 16) on one TPU chip through the bucketed dynamic-LoD path.

BASELINE.md carries no GPU anchor for this workload (the reference's
README only tables the LSTM classifier), so the JSON line reports
absolute target-tokens/sec; the point of the bench is that the
DISTINCTIVE ragged pipeline — DynamicRNN with runtime row-splits,
sequence_expand/softmax/pool attention per step — holds a production
number on chip.  Same windowed run_steps methodology as bench_lstm.py
(per-batch run() walls on this container measure the axon tunnel's
~100 ms dispatch+sync, not the framework).
"""

from __future__ import annotations

import json
import sys

import numpy as np

SRC_DICT = TRG_DICT = 30000
EMB = ENC = DEC = 512
BATCH, SRC_MAX, TRG_MAX = 16, 50, 50
WINDOW = 8


def main():
    import os
    import jax
    global SRC_DICT, TRG_DICT, EMB, ENC, DEC, BATCH, SRC_MAX, TRG_MAX
    global WINDOW
    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    if not on_tpu:
        SRC_DICT = TRG_DICT = 500
        EMB = ENC = DEC = 16
        BATCH, SRC_MAX, TRG_MAX, WINDOW = 4, 10, 10, 3

    import paddle_tpu as fluid
    from paddle_tpu.models.seq2seq import seq_to_seq_net, fake_batch
    import bench

    def run_point(batch):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            avg_cost, _ = seq_to_seq_net(SRC_DICT, TRG_DICT, emb_dim=EMB,
                                         encoder_size=ENC,
                                         decoder_size=DEC)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        main_prog.lod_buckets = True

        n_windows = 3
        windows = [[fake_batch(batch, SRC_MAX, TRG_MAX, SRC_DICT,
                               TRG_DICT, seed=50 * w + i)
                    for i in range(WINDOW)] for w in range(n_windows)]

        def feed_of(w):
            return {k: [b[k] for b in windows[w]]
                    for k in ("src_word", "trg_word", "label")}

        def trg_tokens(w):
            return sum(b["trg_word"][1][0][-1] for b in windows[w])

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            for w in range(n_windows):
                exe.run_steps(main_prog, feed=feed_of(w),
                              fetch_list=[avg_cost.name], steps=WINDOW)
            k = [0]

            def run_once():
                exe.run_steps(main_prog, feed=feed_of(k[0] % n_windows),
                              fetch_list=[avg_cost.name], steps=WINDOW)
                k[0] += 1

            dt, _ = bench.measure_trials(run_once, n_trials=4)
        toks = np.mean([trg_tokens(w) for w in range(n_windows)])
        return toks / dt, dt * 1e3 / WINDOW

    # the reference operating point (batch 16) on stdout; batch 64 shows
    # the same program is batch-scalable (the 16-point is latency-bound
    # by the serial decoder, not a framework ceiling)
    for batch in [BATCH] + ([BATCH * 4] if BATCH >= 16 else []):
        tps, mspb = run_point(batch)
        line = json.dumps({
            "metric": f"seq2seq_attention_tokens_per_sec_per_chip"
                      + ("" if batch == BATCH else f"_b{batch}"),
            "value": round(tps, 2), "unit": "tokens/sec",
            "vs_baseline": None,
            "ms_per_batch": round(mspb, 3), "batch": batch,
        })
        print(line, file=sys.stdout if batch == BATCH else sys.stderr)


if __name__ == "__main__":
    main()
