"""ResNet-50 images/sec/chip benchmark (reference ``benchmark/fluid/resnet.py``
+ ``run.sh`` protocol), the conv half of the BASELINE.json north star.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec", "vs_baseline": R}

``vs_baseline`` is achieved MFU / 0.45.  FLOPs are counted analytically by
walking the built program's conv2d/mul ops (2*MACs fwd, x3 for training:
the filter-grad and input-grad passes each cost about one forward conv) —
elementwise/batch-norm/pool ops are excluded, the standard convnet MFU
convention.  Timing is the median of ``PADDLE_TPU_BENCH_TRIALS`` (default
5) trials of a device-side ``run_steps`` loop after warmup, same
robustness discipline as ``bench.py``.

Run directly (``python bench_resnet.py``), or via ``bench.py`` with
``PADDLE_TPU_BENCH_MODEL=resnet`` (transformer stays the first/default
metric the driver parses).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from bench import measure_trials, peak_flops_per_chip


def program_matmul_flops(block):
    """Forward FLOPs of one pass: sum over conv2d (2*N*Ho*Wo*Co*Ci*kh*kw)
    and mul/matmul (2*M*K*N) ops, from the IR's inferred var shapes."""
    flops = 0
    for op in block.ops:
        if op.type in ("conv2d", "depthwise_conv2d"):
            filt = block.var(op.input("Filter")[0])
            out = block.var(op.output("Output")[0])
            # filter is [Co, Ci/groups, kh, kw] — ci is already the
            # per-group fan-in, so no further division by groups
            co, ci, kh, kw = filt.shape
            n, _, ho, wo = out.shape
            flops += 2 * n * ho * wo * co * ci * kh * kw
        elif op.type in ("mul", "matmul"):
            x = block.var(op.input("X")[0])
            y = block.var(op.input("Y")[0])
            k, n = y.shape[-2], y.shape[-1]
            m = int(np.prod(x.shape)) // k
            flops += 2 * m * k * n
    return flops


def main():
    import jax
    prec = os.environ.get("PADDLE_TPU_MATMUL_PRECISION")
    if prec:
        jax.config.update("jax_default_matmul_precision", prec)
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as R

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    if on_tpu:
        batch = int(os.environ.get("PADDLE_TPU_BENCH_BATCH", "256"))
        image_shape, class_dim, depth = (3, 224, 224), 1000, 50
        # 24 steps/dispatch: this container's tunnel costs ~100 ms per
        # dispatch+sync round trip, which at 8 steps inflated the wall
        # by ~13 ms/step (BENCH_RESNET_CEILING.md r5 addendum)
        warmup_calls, steps = 2, int(
            os.environ.get("PADDLE_TPU_BENCH_STEPS", "24"))
    else:  # tiny smoke config for dev machines
        batch, image_shape, class_dim, depth = 4, (3, 32, 32), 10, 18
        warmup_calls, steps = 1, 2

    main_prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main_prog, startup):
        avg_cost, acc, feeds = R.resnet_train_program(
            batch, class_dim=class_dim, depth=depth,
            image_shape=image_shape)
        opt = fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9)
        opt.minimize(avg_cost)
    fwd_flops = program_matmul_flops(main_prog.global_block())
    main_prog.amp = on_tpu  # bf16 compute, f32 master weights

    rng = np.random.RandomState(0)
    stacked = {
        "image": rng.rand(steps, batch, *image_shape).astype("float32"),
        "label": rng.randint(0, class_dim,
                             size=(steps, batch, 1)).astype("int64"),
    }

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        stacked = {k: jax.device_put(v) for k, v in stacked.items()}
        for _ in range(warmup_calls):
            exe.run_steps(main_prog, feed=stacked,
                          fetch_list=[avg_cost.name], steps=steps)

        last = [None]

        def run_once():
            # run_steps returns numpy (blocks on device) — no extra sync
            # needed before the clock
            last[0] = exe.run_steps(main_prog, feed=stacked,
                                    fetch_list=[avg_cost.name], steps=steps)

        dt, trial_dts = measure_trials(run_once)
        loss = np.asarray(last[0][0])[-1]
        # tenant-proof whole-step device time (executor pt_step scope);
        # best-effort — the headline wall metric must survive a host
        # without the xplane protobuf package
        dev_s = 0.0
        if on_tpu:
            try:
                from paddle_tpu import profiler
                dev_s = profiler.measure_device_seconds(run_once,
                                                        scope="pt_step")
            except Exception as e:
                print(f"# device-time probe unavailable: {e!r}",
                      file=sys.stderr)

    images = batch * steps
    images_per_sec = images / dt
    flops_per_image = 3 * fwd_flops / batch  # fwd + dfilter + dinput convs
    mfu = images_per_sec * flops_per_image / peak_flops_per_chip()

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    step_mss = ", ".join(f"{t / steps * 1e3:.1f}" for t in trial_dts)
    dev_ms = dev_s / steps * 1e3 if dev_s else float("nan")
    print(f"# loss={float(np.asarray(loss).reshape(()))}"
          f" mfu={mfu:.3f} fwd_gflops_per_image={fwd_flops / batch / 1e9:.2f}"
          f" step_ms_median={dt / steps * 1e3:.1f}"
          f" device_ms={dev_ms:.1f}"
          f" trials=[{step_mss}]", file=sys.stderr)


if __name__ == "__main__":
    main()
