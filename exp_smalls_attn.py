"""Experiment: small-S specialized flash attention (round-4 perf item).

At S=256 the shipped flash kernel loses to composed XLA (0.803x): its
grid is (B, H, 1, 1) = 2048 tiny programs, each paying online-softmax
scratch traffic that is pointless when the whole [S, S] score tile fits
VMEM.  This experiment tries a specialization for S_q == S_k <= 1024:

  * fold (B, H) into ONE grid axis with G bh-pairs per program
    (1 grid dim instead of 4);
  * single-pass softmax — scores live in registers/VMEM once, no
    running-max/denominator scratch, no @pl.when init/final phases;
  * ONE backward kernel producing dq, dk, dv together (the shipped path
    runs two kernels, each recomputing the scores).

Times fwd+bwd vs the shipped flash and composed XLA at S in {256, 512},
G in {1, 4, 8, 16}, and checks numerics against the reference path.
Artifact feeding the ops/attention_ops.py integration.
"""

from __future__ import annotations

import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bench import measure_trials
from paddle_tpu.ops.attention_ops import (
    fused_attention, _reference_attention, NEG_INF)

ITERS = 10
HEADS, DIM = 8, 64
TOKENS = 1 << 16


def _causal_bias_2d(S):
    row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    return jnp.where(col > row, NEG_INF, 0.0)


def _smalls_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                       causal, scale, G, S):
    bias = _causal_bias_2d(S) if causal else None
    for g in range(G):
        q = q_ref[g]                      # [S, D]
        k = k_ref[g]
        v = v_ref[g]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = s + (1.0 - mask_ref[g][:, 0].astype(jnp.float32))[None, :] * NEG_INF
        if bias is not None:
            s = s + bias
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[g] = (o / l).astype(o_ref.dtype)
        # residual as (m, log l) SEPARATELY: fl(m + log l) == m when
        # |m| ~ 1e9 (fully-masked row), which breaks bwd's p = exp(s-lse)
        lse_ref[g] = jnp.concatenate([m, jnp.log(l)], axis=1)


def _smalls_bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                       delta_ref, dq_ref, dk_ref, dv_ref, *, causal,
                       scale, G, S):
    bias = _causal_bias_2d(S) if causal else None
    for g in range(G):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        do = do_ref[g]
        m = lse_ref[g][:, 0:1]            # [S, 1]
        logl = lse_ref[g][:, 1:2]         # [S, 1]
        delta = delta_ref[g]              # [S, 1]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = s + (1.0 - mask_ref[g][:, 0].astype(jnp.float32))[None, :] * NEG_INF
        if bias is not None:
            s = s + bias
        p = jnp.exp((s - m) - logl)
        dv_ref[g] = jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[g] = jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[g] = jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def smalls_fwd(q, k, v, k_mask, causal, scale, G):
    B, H, S, D = q.shape
    BH = B * H
    qf = q.reshape(BH, S, D)
    kf = k.reshape(BH, S, D)
    vf = v.reshape(BH, S, D)
    maskf = jnp.broadcast_to(k_mask[:, None, :], (B, H, S)) \
        .reshape(BH, S, 1)
    out, lse = pl.pallas_call(
        functools.partial(_smalls_fwd_kernel, causal=causal, scale=scale,
                          G=G, S=S),
        grid=(BH // G,),
        in_specs=[
            pl.BlockSpec((G, S, D), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((G, S, D), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((G, S, D), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((G, S, 1), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((G, S, D), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((G, S, 2), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 2), jnp.float32),
        ],
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S, 2)


def smalls_bwd(q, k, v, k_mask, o, lse, g, causal, scale, G):
    B, H, S, D = q.shape
    BH = B * H
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    maskf = jnp.broadcast_to(k_mask[:, None, :], (B, H, S)) \
        .reshape(BH, S, 1)
    flat = lambda x: x.reshape(BH, S, -1)
    spec3 = pl.BlockSpec((G, S, D), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM)
    spec1 = pl.BlockSpec((G, S, 1), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM)
    spec2 = pl.BlockSpec((G, S, 2), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_smalls_bwd_kernel, causal=causal, scale=scale,
                          G=G, S=S),
        grid=(BH // G,),
        in_specs=[
            spec3, spec3, spec3, spec1,
            spec3, spec2, spec1,
        ],
        out_specs=[spec3, spec3, spec3],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
    )(flat(q), flat(k), flat(v), maskf, flat(g), lse.reshape(BH, S, 2),
      delta.reshape(BH, S, 1))
    unflat = lambda x: x.reshape(B, H, S, D)
    return unflat(dq), unflat(dk), unflat(dv)


def make_smalls_attention(G):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
    def attn(q, k, v, k_mask, causal, scale):
        out, _ = fwd(q, k, v, k_mask, causal, scale)
        return out

    def fwd(q, k, v, k_mask, causal, scale):
        out, lse = smalls_fwd(q, k, v, k_mask, causal, scale, G)
        return out, (q, k, v, k_mask, out, lse)

    def bwd(causal, scale, res, g):
        q, k, v, k_mask, o, lse = res
        dq, dk, dv = smalls_bwd(q, k, v, k_mask, o, lse, g, causal,
                                scale, G)
        return dq, dk, dv, None

    attn.defvjp(fwd, bwd)
    return attn


def check_numerics(S=256, B=4):
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, HEADS, S, DIM), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
    k_mask = (jax.random.uniform(jax.random.PRNGKey(3), (B, S))
              > 0.1).astype(jnp.bfloat16)
    scale = DIM ** -0.5
    attn = make_smalls_attention(G=4)

    for causal in (False, True):
        def loss_small(q, k, v):
            return jnp.sum(attn(q, k, v, k_mask, causal, scale)
                           .astype(jnp.float32))

        def loss_ref(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, k_mask, causal, scale).astype(jnp.float32))

        o_s = attn(q, k, v, k_mask, causal, scale)
        o_r = _reference_attention(q, k, v, k_mask, causal, scale)
        err = jnp.max(jnp.abs(o_s.astype(jnp.float32)
                              - o_r.astype(jnp.float32)))
        gs = jax.jit(jax.grad(loss_small, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                         - b.astype(jnp.float32))))
                   for a, b in zip(gs, gr))
        print(f"# numerics causal={causal}: fwd maxerr={float(err):.4f} "
              f"bwd maxerr={gerr:.4f}", file=sys.stderr)
        assert float(err) < 0.1 and gerr < 0.5, "numerics mismatch"


def time_variant(step_fn, q, k, v):
    g = step_fn(q, k, v)
    np.asarray(g[0][0, 0, 0, 0])  # compile + settle

    def run_once():
        qq = q
        last = None
        for _ in range(ITERS):
            gg = step_fn(qq, k, v)
            qq = qq + 0.0 * gg[0]
            last = gg
        np.asarray(last[0][0, 0, 0, 0])

    dt, _ = measure_trials(run_once, n_trials=3)
    return dt / ITERS * 1e3


def main():
    check_numerics()
    scale = DIM ** -0.5
    for S in (256, 512):
        B = TOKENS // S
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, HEADS, S, DIM), jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
        k_mask = jnp.ones((B, S), jnp.bfloat16)
        row = {"S": S, "B": B}

        def mk(fn):
            def loss(q, k, v):
                return jnp.sum(fn(q, k, v).astype(jnp.float32))
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        row["xla_ms"] = round(time_variant(
            mk(lambda q, k, v: fused_attention(
                q, k, v, k_mask, True, scale, False)), q, k, v), 3)
        row["flash_ms"] = round(time_variant(
            mk(lambda q, k, v: fused_attention(
                q, k, v, k_mask, True, scale, True)), q, k, v), 3)
        for G in (1, 4, 8, 16):
            attn = make_smalls_attention(G)
            try:
                row[f"smalls_G{G}_ms"] = round(time_variant(
                    mk(lambda q, k, v, a=attn: a(
                        q, k, v, k_mask, True, scale)), q, k, v), 3)
            except Exception as e:
                row[f"smalls_G{G}_ms"] = f"ERR {type(e).__name__}"
                print(f"# S={S} G={G}: {e}", file=sys.stderr)
        print(json.dumps(row))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
