"""Device-time decomposition of the stacked-LSTM batch (r5): wall vs
device, per-IR-op table — where do 151 ms/batch go?"""
import os
import tempfile
import time

os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"

import numpy as np
import jax

import paddle_tpu as fluid
from paddle_tpu import profiler
from paddle_tpu.models.stacked_lstm import stacked_lstm_net, fake_batch

DICT, EMB, HIDDEN, LAYERS, BATCH, SEQ = 30000, 512, 256, 2, 64, 100
N = 8

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    avg_cost, acc, _ = stacked_lstm_net(DICT, emb_dim=EMB,
                                        hidden_dim=HIDDEN, n_layers=LAYERS)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
main.lod_buckets = True

feeds = [fake_batch(BATCH, SEQ, DICT, seed=i) for i in range(N)]
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    for b in feeds:
        exe.run(main, feed=b, fetch_list=[avg_cost.name])
    t0 = time.perf_counter()
    for b in feeds:
        exe.run(main, feed=b, fetch_list=[avg_cost.name])
    wall = (time.perf_counter() - t0) / N
    td = tempfile.mkdtemp(prefix="lstmprof_")
    jax.profiler.start_trace(td)
    for b in feeds:
        exe.run(main, feed=b, fetch_list=[avg_cost.name])
    jax.profiler.stop_trace()
    dev = profiler.scope_device_seconds(td, "ptop_") / N
    _, rows = profiler.compiled_op_table(td)
    print(f"wall {wall * 1e3:.1f} ms/batch   device(ptop) "
          f"{dev * 1e3:.1f} ms/batch")
    for op, calls, sec in rows[:14]:
        print(f"  {op:30s} {calls:6d} {sec * 1e3 / N:9.3f} ms/batch")
