"""Autoscale benchmark: the closed control loop under open-loop load.

The drill the fleet controller exists for: a 5× traffic step from the
replay harness (``paddle_tpu.fleet.traffic``) hits a 1-replica fleet.
Fixed-N rides the queue into SLO breach; the controller fleet senses
the p99 pressure, engages the admission ladder (429 + Retry-After —
never a silent drop or a deadline-burning queue wait), and promotes
warm standbys — pre-warmed through the persistent XLA compile cache,
so scale-up is a lease registration, not a compile.  A chaos variant
hard-kills a replica mid-ramp (``fleet.replica.kill``) and counts
lost *accepted* requests, which must be zero.

Device work is MODELED WITH A SLEEP — the ``serving.predict``
failpoint (armed ``delay:SECS``) fires inside the predictor lock, so
each replica serves serially at a fixed service time (the bench-host
cost model shared with ``bench_fleet.py``).

    python bench_autoscale.py --duration 8 --out BENCH_AUTOSCALE.json
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import tempfile
import threading
import time

import numpy as np

from bench_fleet import build_model


def _slo_spec(p99_slo_ms, interval=0.1):
    return {
        "version": 1,
        "interval_seconds": interval,
        "sustained_breaches": 2,
        "objectives": [
            {"name": "request-p99", "kind": "quantile",
             "series": "fleet.request_seconds", "quantile": "p99",
             "max": p99_slo_ms / 1000.0},
        ],
    }


def _policy(max_replicas, standby_pool, tick=0.15):
    return {
        "version": 1,
        "interval_seconds": tick,
        "min_replicas": 1,
        "max_replicas": max_replicas,
        "standby_pool": standby_pool,
        "ready_timeout_seconds": 120.0,
        # react on the pressure MARGIN, well before the p99 nears the
        # threshold: by the time the p99 signal itself breaches, the
        # queue already holds requests that blew the budget — scaling
        # at 35% of the SLO keeps the step transient inside it
        "scale_up": {"pressure_ratio": 0.35, "sustained_ticks": 2,
                     "cooldown_seconds": 0.8},
        "scale_down": {"idle_rps_per_replica": 0.0,
                       "sustained_ticks": 1000,
                       "cooldown_seconds": 1000.0},
        # the ladder is the FAST line of defense: requests already
        # queued when new capacity lands still finish late (shedding
        # never shortens an existing queue), so the whole-run p99 is
        # ~the queue wait at engage time — engage at 25% of the SLO
        # and shed half the arrivals at the first rung while the
        # promotion is in flight
        "degrade": {"ladder": [0.0, 0.5, 0.75], "engage_ratio": 0.25,
                    "recover_ticks": 4, "retry_after_seconds": 0.5},
    }


def _send_factory(router_addr, payload_bytes, deadline_ms):
    """One open-loop request: raw HTTP POST, no client-side retry —
    the replay measures what the FLEET returns, outcome by outcome."""
    import http.client
    host, port = router_addr

    def send(i):
        conn = http.client.HTTPConnection(
            host, port, timeout=deadline_ms / 1000.0 + 5.0)
        try:
            conn.request("POST", "/predict", payload_bytes,
                         {"Content-Type": "application/json",
                          "X-Deadline-Ms": str(int(deadline_ms))})
            resp = conn.getresponse()
            resp.read()
            return {"status": resp.status,
                    "retry_after": resp.getheader("Retry-After")}
        finally:
            conn.close()

    return send


def run_autoscale(model_dir, controller_on, duration=8.0,
                  service_ms=40.0, base_rps=5.0, peak_rps=25.0,
                  step_at=None, p99_slo_ms=500.0, deadline_ms=2000.0,
                  seed=7, kill_mid_ramp=False, fixed_replicas=1,
                  max_replicas=3, standby_pool=2, feature_dim=4):
    """One mode of the drill: master + router (+SLO watchdog) + a
    starting fleet, open-loop step traffic for ``duration`` seconds;
    with ``controller_on`` a :class:`FleetController` with a prewarmed
    standby pool closes the loop.  Returns a stats dict."""
    from paddle_tpu import profiler
    from paddle_tpu.fault import chaos
    from paddle_tpu.fleet import FleetController, FleetReplica, \
        FleetRouter
    from paddle_tpu.fleet.traffic import TrafficReplay, step
    from paddle_tpu.parallel.master import MasterServer, MasterService
    from paddle_tpu.serving import ServingClient

    profiler.runtime_metrics.reset()
    chaos.clear()
    chaos.inject("serving.predict", delay=service_ms / 1000.0)
    if step_at is None:
        step_at = duration * 0.25
    svc = MasterService(replica_ttl=5.0)
    master = MasterServer(svc, port=0)
    master.start_background()
    maddr = f"{master.addr[0]}:{master.addr[1]}"

    def make_replica(rid):
        return FleetReplica(model_dir, maddr, replica_id=rid,
                            lease_ttl=5.0, heartbeat_interval=0.2,
                            warmup=True, warmup_batch_sizes=(1,),
                            request_timeout=30.0)

    replicas = [make_replica(f"fix{i}").start()
                for i in range(fixed_replicas)]
    router = FleetRouter(master_addr=maddr, poll_interval=0.1,
                         slo_spec=_slo_spec(p99_slo_ms))
    router.start_background()
    controller = None
    killer = None
    counters = profiler.runtime_metrics.counter
    try:
        wait_until = time.time() + 30
        while len(router.live_replicas()) < fixed_replicas and \
                time.time() < wait_until:
            time.sleep(0.05)
        payload = {"feeds": {"x": np.random.RandomState(0)
                             .rand(1, feature_dim).astype("float32")
                             .tolist()}}
        payload_bytes = json.dumps(payload).encode()
        warm = ServingClient(router.addr)
        for _ in range(fixed_replicas * 2):  # touch replicas pre-clock
            warm.predict({"x": np.random.RandomState(0)
                          .rand(1, feature_dim).astype("float32")})

        cache_before = (counters("compile_cache.hits"),
                        counters("compile_cache.misses"))
        if controller_on:
            sb = itertools.count()
            controller = FleetController(
                router,
                policy=_policy(max_replicas, standby_pool),
                standby_factory=lambda: make_replica(f"sb{next(sb)}"))
            controller.prewarm()
            controller.start()
        cache_after_warm = (counters("compile_cache.hits"),
                            counters("compile_cache.misses"))

        if kill_mid_ramp:
            killer = threading.Timer(
                step_at + 1.0,
                lambda: chaos.inject("fleet.replica.kill", error=True,
                                     times=1))
            killer.daemon = True
            killer.start()

        replay = TrafficReplay(
            _send_factory(router.addr, payload_bytes, deadline_ms),
            step(base_rps, peak_rps, step_at),
            duration, seed=seed, max_inflight=256)
        traffic = replay.run()

        killed = [r.replica_id for r in replicas if r.killed]
        state = controller.state() if controller is not None else None
        if controller is not None:
            with controller._lock:
                killed += [r.replica_id for r in controller._owned
                           if r.killed]
        return {
            "mode": "controller" if controller_on else "fixed",
            "replicas_start": fixed_replicas,
            "replicas_end": len(router.live_replicas()),
            "traffic": traffic,
            "p99_ms": traffic["latency_ms"]["p99"],
            "slo_p99_ms": p99_slo_ms,
            "held_slo": (traffic["latency_ms"]["p99"] or 0.0)
            <= p99_slo_ms,
            "scale_ups": counters("controller.scale_ups"),
            "scale_downs": counters("controller.scale_downs"),
            "admission_sheds": counters("fleet.admission_shed"),
            "router_sheds": counters("fleet.shed"),
            "standby_compile_cache": {
                "hits_delta": cache_after_warm[0] - cache_before[0],
                "misses_delta": cache_after_warm[1] - cache_before[1],
            },
            "killed": killed,
            "controller": state,
        }
    finally:
        if killer is not None:
            killer.cancel()
        chaos.clear()
        if controller is not None:
            controller.shutdown(drain_owned=True)
        for r in replicas:
            if not r.killed:
                r.drain()
        router.shutdown()
        master.shutdown()


def run_bench(duration=8.0, service_ms=40.0, base_rps=6.0,
              peak_rps=30.0, p99_slo_ms=500.0, deadline_ms=2000.0,
              seed=7, model_dir=None, max_replicas=3, standby_pool=2):
    """Fixed-1 vs controller fleet under the same seeded 5× step, then
    the mid-ramp kill drill on the controller fleet; returns the
    JSON-ready summary.  ``PADDLE_TPU_COMPILE_CACHE`` is pointed at a
    shared temp dir for the whole run, so the fixed pass populates the
    cache and every standby warm afterwards must HIT it."""
    own = model_dir is None
    if own:
        model_dir = build_model(
            tempfile.mkdtemp(prefix="ptauto_") + "/model")
    prev_cache = os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    os.environ["PADDLE_TPU_COMPILE_CACHE"] = \
        tempfile.mkdtemp(prefix="ptauto_cache_")
    try:
        kw = dict(duration=duration, service_ms=service_ms,
                  base_rps=base_rps, peak_rps=peak_rps,
                  p99_slo_ms=p99_slo_ms, deadline_ms=deadline_ms,
                  seed=seed, max_replicas=max_replicas,
                  standby_pool=standby_pool)
        fixed = run_autoscale(model_dir, controller_on=False, **kw)
        ctrl = run_autoscale(model_dir, controller_on=True, **kw)
        drill = run_autoscale(model_dir, controller_on=True,
                              kill_mid_ramp=True, **kw)
    finally:
        if prev_cache is None:
            os.environ.pop("PADDLE_TPU_COMPILE_CACHE", None)
        else:
            os.environ["PADDLE_TPU_COMPILE_CACHE"] = prev_cache
    sheds_without = sum(m["traffic"]["shed_without_hint"]
                       for m in (fixed, ctrl, drill))
    return {
        "duration_sec": duration,
        "service_ms": service_ms,
        "base_rps": base_rps,
        "peak_rps": peak_rps,
        "slo_p99_ms": p99_slo_ms,
        "deadline_ms": deadline_ms,
        "seed": seed,
        "modes": {"fixed": fixed, "controller": ctrl},
        "kill_drill": drill,
        "sheds_without_retry_after": sheds_without,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--service-ms", type=float, default=40.0)
    ap.add_argument("--base-rps", type=float, default=5.0)
    ap.add_argument("--peak-rps", type=float, default=25.0)
    ap.add_argument("--slo-p99-ms", type=float, default=500.0)
    ap.add_argument("--deadline-ms", type=float, default=2000.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--standby-pool", type=int, default=2)
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(duration=args.duration,
                        service_ms=args.service_ms,
                        base_rps=args.base_rps, peak_rps=args.peak_rps,
                        p99_slo_ms=args.slo_p99_ms,
                        deadline_ms=args.deadline_ms, seed=args.seed,
                        max_replicas=args.max_replicas,
                        standby_pool=args.standby_pool)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("autoscale", summary, args,
                                   "bench_autoscale.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
