"""Profiler bridge (reference ``python/paddle/fluid/profiler.py`` over the
C++ host/device tracer ``paddle/fluid/platform/profiler.cc`` + CUPTI
``device_tracer.h:32``).

TPU-native realization: ``jax.profiler`` traces (viewable in
TensorBoard/XProf) carry both host and device timelines — the role CUPTI
plays on GPU.  Op-level annotation uses ``jax.named_scope`` markers inserted
by the executor; ``profiler(state, sorted_key)`` context mirrors the
reference API.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ["cuda_profiler", "reset_profiler", "profiler",
           "start_profiler", "stop_profiler", "enable_op_profiling",
           "disable_op_profiling", "op_profile_table", "op_profiler"]

_trace_dir = None
_start_time = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Name kept for API parity; on TPU this is an XLA/XProf trace."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    """Clear collected op-level events (reference ``profiler.py``
    reset_profiler)."""
    global _op_events
    _op_events = {}


def start_profiler(state="All", profile_path="/tmp/paddle_tpu_profile"):
    global _trace_dir, _start_time
    _trace_dir = profile_path
    _start_time = time.time()
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:  # already tracing
        pass


def stop_profiler(sorted_key=None, profile_path=None):
    global _trace_dir
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    _trace_dir = None


@contextlib.contextmanager
def profiler(state="All", sorted_key=None,
             profile_path="/tmp/paddle_tpu_profile"):
    """reference ``profiler.py:76``."""
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# op-level aggregation table (reference EnableProfiler/DisableProfiler,
# ``platform/profiler.h:110-115``: sorted per-op-type event tables).
#
# On TPU the compiled path fuses ops away, so op-level timing runs the
# block in the executor's op-by-op interpret mode with a device sync per op
# — the same overhead FLAGS_benchmark adds on the reference.
# ---------------------------------------------------------------------------

_op_profiling = False
_op_events = {}


def op_profiling_enabled():
    return _op_profiling


def enable_op_profiling():
    """Start collecting per-op timings; forces interpret-mode execution."""
    global _op_profiling, _op_events
    _op_profiling = True
    _op_events = {}


def disable_op_profiling():
    global _op_profiling
    _op_profiling = False


@contextlib.contextmanager
def record_op(op_type, ctx=None):
    t0 = time.perf_counter()
    with jax.named_scope(op_type):
        yield
    # sync so the interval covers device work (reference implicit Wait)
    if ctx is not None:
        for v in ctx.outputs.values():
            if hasattr(v, "block_until_ready"):
                try:
                    v.block_until_ready()
                except Exception:
                    pass
    dt = time.perf_counter() - t0
    ev = _op_events.setdefault(op_type, [0, 0.0, 0.0])
    ev[0] += 1
    ev[1] += dt
    ev[2] = max(ev[2], dt)


def op_profile_table(sorted_key="total"):
    """Sorted per-op aggregation table as a string (reference
    ``profiler.h`` PrintProfiler: Event/Calls/Total/Min/Max/Ave)."""
    keys = {"total": 1, "calls": 0, "max": 2,
            "ave": lambda item: item[1][1] / max(item[1][0], 1)}
    k = keys.get(sorted_key or "total", 1)
    rows = sorted(_op_events.items(),
                  key=(k if callable(k) else (lambda item, i=k: item[1][i])),
                  reverse=True)
    lines = [f"{'Event':<28}{'Calls':>8}{'Total(ms)':>12}"
             f"{'Ave(ms)':>12}{'Max(ms)':>12}"]
    for op_type, (calls, total, mx) in rows:
        lines.append(f"{op_type:<28}{calls:>8}{total * 1e3:>12.3f}"
                     f"{total / max(calls, 1) * 1e3:>12.3f}{mx * 1e3:>12.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def op_profiler(sorted_key="total"):
    """Context manager: profile per-op and print the table on exit."""
    enable_op_profiling()
    try:
        yield
    finally:
        disable_op_profiling()
        print(op_profile_table(sorted_key))
