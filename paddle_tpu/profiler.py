"""Profiler bridge (reference ``python/paddle/fluid/profiler.py`` over the
C++ host/device tracer ``paddle/fluid/platform/profiler.cc`` + CUPTI
``device_tracer.h:32``).

TPU-native realization: ``jax.profiler`` traces (viewable in
TensorBoard/XProf) carry both host and device timelines — the role CUPTI
plays on GPU.  Op-level annotation uses ``jax.named_scope`` markers inserted
by the executor; ``profiler(state, sorted_key)`` context mirrors the
reference API.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time

import jax

__all__ = ["cuda_profiler", "reset_profiler", "profiler",
           "start_profiler", "stop_profiler", "enable_op_profiling",
           "disable_op_profiling", "op_profile_table", "op_profiler",
           "RuntimeMetrics", "runtime_metrics", "record_latency",
           "install_jax_compile_listeners"]

_trace_dir = None
_start_time = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Name kept for API parity; on TPU this is an XLA/XProf trace."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    """Clear collected op-level events (reference ``profiler.py``
    reset_profiler)."""
    global _op_events
    _op_events = {}


def start_profiler(state="All", profile_path="/tmp/paddle_tpu_profile"):
    global _trace_dir, _start_time
    _trace_dir = profile_path
    _start_time = time.time()
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:  # already tracing
        pass


def stop_profiler(sorted_key=None, profile_path=None):
    global _trace_dir
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    _trace_dir = None


@contextlib.contextmanager
def profiler(state="All", sorted_key=None,
             profile_path="/tmp/paddle_tpu_profile"):
    """reference ``profiler.py:76``."""
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# op-level aggregation table (reference EnableProfiler/DisableProfiler,
# ``platform/profiler.h:110-115``: sorted per-op-type event tables).
#
# On TPU the compiled path fuses ops away, so op-level timing runs the
# block in the executor's op-by-op interpret mode with a device sync per op
# — the same overhead FLAGS_benchmark adds on the reference.
# ---------------------------------------------------------------------------

_op_profiling = False
_op_events = {}


def op_profiling_enabled():
    return _op_profiling


def enable_op_profiling():
    """Start collecting per-op timings; forces interpret-mode execution."""
    global _op_profiling, _op_events
    _op_profiling = True
    _op_events = {}


def disable_op_profiling():
    global _op_profiling
    _op_profiling = False


@contextlib.contextmanager
def record_op(op_type, ctx=None):
    t0 = time.perf_counter()
    with jax.named_scope(op_type):
        yield
    # sync so the interval covers device work (reference implicit Wait)
    if ctx is not None:
        for v in ctx.outputs.values():
            if hasattr(v, "block_until_ready"):
                try:
                    v.block_until_ready()
                except Exception:
                    pass
    dt = time.perf_counter() - t0
    ev = _op_events.setdefault(op_type, [0, 0.0, 0.0])
    ev[0] += 1
    ev[1] += dt
    ev[2] = max(ev[2], dt)


def op_profile_table(sorted_key="total"):
    """Sorted per-op aggregation table as a string (reference
    ``profiler.h`` PrintProfiler: Event/Calls/Total/Min/Max/Ave)."""
    keys = {"total": 1, "calls": 0, "max": 2,
            "ave": lambda item: item[1][1] / max(item[1][0], 1)}
    k = keys.get(sorted_key or "total", 1)
    rows = sorted(_op_events.items(),
                  key=(k if callable(k) else (lambda item, i=k: item[1][i])),
                  reverse=True)
    lines = [f"{'Event':<28}{'Calls':>8}{'Total(ms)':>12}"
             f"{'Ave(ms)':>12}{'Max(ms)':>12}"]
    for op_type, (calls, total, mx) in rows:
        lines.append(f"{op_type:<28}{calls:>8}{total * 1e3:>12.3f}"
                     f"{total / max(calls, 1) * 1e3:>12.3f}{mx * 1e3:>12.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def op_profiler(sorted_key="total"):
    """Context manager: profile per-op and print the table on exit."""
    enable_op_profiling()
    try:
        yield
    finally:
        disable_op_profiling()
        print(op_profile_table(sorted_key))


# ---------------------------------------------------------------------------
# compiled-path per-op attribution (round 3; reference platform/profiler.h
# RecordEvent:110 attributes real run time to ops — here the executor
# wraps every op lowering in jax.named_scope, XLA carries the scope into
# each HLO instruction's op_name metadata, and a trace of the COMPILED
# step is aggregated back to IR op names)
# ---------------------------------------------------------------------------

_SCOPE_PREFIX = "ptop_"


def op_scope_name(op):
    """named_scope label for an IR op: ptop_<type>__<primary output>.
    Dots/slashes are scope separators in XLA metadata, so sanitize."""
    outs = op.output_arg_names
    tag = outs[0] if outs else ""
    return _SCOPE_PREFIX + f"{op.type}__{tag}".replace(".", "_") \
        .replace("/", "_")


def parse_op_scope(hlo_op_name):
    """Deepest ptop_ scope component of an HLO op_name path, as
    (op_type, output_tag), or None."""
    hit = None
    for part in str(hlo_op_name).split("/"):
        if part.startswith(_SCOPE_PREFIX):
            hit = part[len(_SCOPE_PREFIX):]
    if hit is None:
        return None
    op_type, _, tag = hit.partition("__")
    return op_type, tag


def iter_trace_events(trace_dir, device_only=False, exclude_async=False):
    """Yield ``(name_candidates, duration_ps)`` for every event in a
    jax.profiler trace (xplane protos under ``trace_dir``).  The scope
    label appears either in the event name or in the tf_op/long_name stat
    depending on the backend — callers match against ALL candidates.
    ``device_only`` restricts to accelerator planes (``/device:...``) so
    host Python-tracer events cannot pollute device-time sums;
    ``exclude_async`` drops 'Async XLA Ops' lines, whose overlapping DMA
    durations multi-count wall time.  Shared by :func:`compiled_op_table`
    and the benchmark harnesses."""
    for plane in _iter_xplanes(trace_dir):
        if device_only and not plane.name.startswith("/device:"):
            continue
        statmeta = plane.stat_metadata
        evmeta = plane.event_metadata
        for line in plane.lines:
            if exclude_async and "async" in line.name.lower():
                continue
            for ev in line.events:
                m = evmeta[ev.metadata_id]
                cands = [m.name, getattr(m, "display_name", "")]
                for st in list(ev.stats) + list(m.stats):
                    sname = statmeta[st.metadata_id].name
                    if sname in ("tf_op", "long_name", "name"):
                        if st.str_value:
                            cands.append(st.str_value)
                        elif st.ref_value:
                            cands.append(
                                statmeta[st.ref_value].name)
                yield cands, ev.duration_ps


def measure_device_seconds(fn, scope=None):
    """Run ``fn()`` under a jax.profiler trace and return its DEVICE
    seconds — total busy time, or only events matching the ``scope``
    substring when given.  Owns the trace-dir lifecycle and the
    pure-python protobuf env the xplane parser needs; wall clocks on
    this backend carry dispatch/sync latencies, so this is the shared
    measurement harness for the bench scripts (exp_resnet_*.py)."""
    import os
    import shutil
    import tempfile

    import jax

    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                          "python")
    td = tempfile.mkdtemp(prefix="pttrace_")
    jax.profiler.start_trace(td)
    try:
        fn()
    finally:
        jax.profiler.stop_trace()
    try:
        if scope is not None:
            return scope_device_seconds(td, scope)
        return device_busy_seconds(td)
    finally:
        shutil.rmtree(td, ignore_errors=True)


def _iter_xplanes(trace_dir):
    """Yield every plane of every xplane proto under ``trace_dir``."""
    import glob as _glob

    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError:  # pragma: no cover
        from tsl.profiler.protobuf import xplane_pb2  # type: ignore

    for path in _glob.glob(str(trace_dir) + "/**/*.xplane.pb",
                           recursive=True):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        yield from xs.planes


def device_busy_seconds(trace_dir):
    """Busy device seconds of a trace: per accelerator plane, the op
    timeline is the line named 'XLA Ops' (span lines like 'Steps' /
    'XLA Modules' include on-device idle gaps, and 'Async XLA Ops' holds
    OVERLAPPING DMA copies whose durations multi-count wall time).  Falls
    back to the max non-async line sum when no 'XLA Ops' line exists.

    SHARED-CHIP caveat (measured, exp_probe_trace.py): the device tracer
    records EVERY program on the chip during the window — other tenants'
    modules included — so this total can exceed your own program's time.
    When that matters, wrap your computation in ``jax.named_scope`` and
    use :func:`scope_device_seconds` / :func:`measure_device_seconds`
    with ``scope=``, which foreign events cannot match."""
    busy = 0.0
    for plane in _iter_xplanes(trace_dir):
        if not plane.name.startswith("/device:"):
            continue
        sums = {}
        for line in plane.lines:
            if "async" in line.name.lower():
                continue
            sums[line.name] = sums.get(line.name, 0) + sum(
                ev.duration_ps for ev in line.events)
        if "XLA Ops" in sums:
            busy += sums["XLA Ops"] / 1e12
        elif sums:
            busy += max(sums.values()) / 1e12
    return busy


def scope_device_seconds(trace_dir, substring):
    """Total device seconds of events whose any name candidate contains
    ``substring`` — the micro-benchmark counterpart of
    :func:`compiled_op_table` (wall clocks on this backend are poisoned
    by dispatch/sync latency; device time is the ground truth)."""
    total_ps = 0
    for cands, dur in iter_trace_events(trace_dir, device_only=True,
                                        exclude_async=True):
        if any(substring in c for c in cands):
            total_ps += dur
    return total_ps / 1e12


def compiled_op_table(trace_dir, sorted_key="total"):
    """Aggregate a jax.profiler trace (xplane protos under ``trace_dir``)
    into per-IR-op device time, keyed by the named_scope labels the
    executor emitted.  Returns (table_string, rows) where rows =
    [(op_type, calls, total_seconds)] sorted descending."""
    import collections

    agg = collections.Counter()
    calls = collections.Counter()
    # exclude_async: overlapping DMA durations otherwise inflate per-op
    # totals past wall time (the r3 ResNet conv attribution suffered this)
    for cands, dur in iter_trace_events(trace_dir, exclude_async=True):
        for c in cands:
            parsed = parse_op_scope(c)
            if parsed is not None:
                agg[parsed[0]] += dur / 1e12
                calls[parsed[0]] += 1
                break
    rows = sorted(((t, calls[t], s) for t, s in agg.items()),
                  key=lambda r: r[1 if sorted_key == "calls" else 2],
                  reverse=True)
    lines = [f"{'Event':<28}{'Calls':>8}{'Total(ms)':>12}{'Ave(ms)':>12}"]
    for op_type, n, total in rows:
        lines.append(f"{op_type:<28}{n:>8}{total * 1e3:>12.3f}"
                     f"{total / max(n, 1) * 1e3:>12.3f}")
    return "\n".join(lines), rows


# ---------------------------------------------------------------------------
# runtime metrics surface (serving/compile hot path): counters, latency
# percentiles, and small-value histograms, exported via the inference
# server's /stats endpoint and `paddle_tpu stats`.  The reference exposes
# analogous counters through its pserver/master Prometheus handlers
# (go/pserver/service.go); here one process-wide registry serves the
# executor (jit-cache hits/evictions, compile seconds), the persistent
# XLA compilation cache (hits/misses via jax monitoring events), and the
# serving batcher (request latency, batch occupancy).
# ---------------------------------------------------------------------------

_LATENCY_WINDOW = 2048  # samples kept per series for percentile estimates


def _nearest_rank(sorted_xs, q):
    """Nearest-rank percentile over an ascending-sorted list (shared by
    percentiles() and snapshot() so the two can never drift)."""
    if not sorted_xs:
        return None
    i = min(len(sorted_xs) - 1,
            max(0, int(round(q / 100.0 * len(sorted_xs))) - 1))
    return sorted_xs[i]


class RuntimeMetrics:
    """Thread-safe process-wide counters + bounded latency reservoirs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = collections.Counter()
        self._series = {}       # name -> deque[float] (bounded window)
        self._series_agg = {}   # name -> [count, total]  (unwindowed)
        self._hist = {}         # name -> Counter (small integer values)
        self._gauges = {}       # name -> float (last-write-wins level)

    # -- writers -------------------------------------------------------
    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def observe(self, name, value):
        """Record one sample (seconds, rows, ...) into a bounded window."""
        with self._lock:
            d = self._series.get(name)
            if d is None:
                d = self._series[name] = collections.deque(
                    maxlen=_LATENCY_WINDOW)
                self._series_agg[name] = [0, 0.0]
            d.append(float(value))
            agg = self._series_agg[name]
            agg[0] += 1
            agg[1] += float(value)

    def bucket(self, name, key):
        """Histogram over small discrete values (batch occupancy)."""
        with self._lock:
            self._hist.setdefault(name, collections.Counter())[int(key)] += 1

    def set_gauge(self, name, value):
        """Instantaneous level (queue depth, pool size): last write wins,
        unlike observe()'s sample series."""
        with self._lock:
            self._gauges[name] = float(value)

    # -- readers -------------------------------------------------------
    def counter(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name):
        with self._lock:
            return self._gauges.get(name)

    def percentiles(self, name, qs=(50, 95, 99)):
        """Window percentiles of ``name``; an unknown or empty series
        yields None per quantile (never raises — dashboards poll series
        that may not have emitted yet)."""
        with self._lock:
            d = self._series.get(name)
            xs = sorted(d) if d else []
        return {f"p{q}": _nearest_rank(xs, q) for q in qs}

    def snapshot(self):
        """One JSON-serializable dict of everything (the /stats body)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hist = {n: {str(k): v for k, v in sorted(c.items())}
                    for n, c in self._hist.items()}
            series = {n: (list(d), list(self._series_agg[n]))
                      for n, d in self._series.items()}
        latency = {}
        for name, (window, (count, total)) in series.items():
            xs = sorted(window)
            entry = {"count": count, "total": total,
                     "mean": (total / count) if count else None}
            for q in (50, 95, 99):
                entry[f"p{q}"] = _nearest_rank(xs, q)
            # 1/mean — a true rate ONLY for serially-recorded series
            # (executor.step_seconds = steps/sec); for concurrent
            # series (request latencies) it is NOT throughput — divide
            # a request counter by wall time instead
            entry["per_sec_serial"] = (count / total) if total else None
            latency[name] = entry
        return {"counters": counters, "series": latency,
                "histograms": hist, "gauges": gauges}

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._series.clear()
            self._series_agg.clear()
            self._hist.clear()
            self._gauges.clear()


runtime_metrics = RuntimeMetrics()


@contextlib.contextmanager
def record_latency(name, metrics=None):
    """Time the body and observe it as one sample of ``name``.

    A raising body still has its elapsed time observed (failures are
    often the SLOW samples — dropping them would flatter the
    percentiles) and additionally bumps the ``<name>.errors`` counter,
    so error-rate and latency stay attributable to the same series."""
    m = metrics or runtime_metrics
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        m.observe(name, time.perf_counter() - t0)
        m.inc(name + ".errors")
        raise
    else:
        m.observe(name, time.perf_counter() - t0)


_jax_listeners_installed = False


def install_jax_compile_listeners():
    """Mirror jax's compile/compilation-cache monitoring events into the
    runtime metrics registry (idempotent):

    - ``compile_cache.hits`` / ``compile_cache.misses``: persistent XLA
      compilation-cache outcomes (PADDLE_TPU_COMPILE_CACHE) — a warm
      restart shows hits where a cold one shows misses;
    - ``compile.backend_seconds`` / ``compile.trace_seconds`` /
      ``compile.lower_seconds``: where compile time goes (XLA backend vs
      jaxpr trace vs MLIR lowering).
    """
    global _jax_listeners_installed
    if _jax_listeners_installed:
        return True
    try:
        from jax._src import monitoring
    except ImportError:  # pragma: no cover - monitoring moved/absent
        return False

    _EVENT_COUNTERS = {
        "/jax/compilation_cache/cache_hits": "compile_cache.hits",
        "/jax/compilation_cache/cache_misses": "compile_cache.misses",
    }
    _DURATION_SERIES = {
        "/jax/core/compile/backend_compile_duration":
            "compile.backend_seconds",
        "/jax/core/compile/jaxpr_trace_duration": "compile.trace_seconds",
        "/jax/core/compile/jaxpr_to_mlir_module_duration":
            "compile.lower_seconds",
    }

    def _on_event(event, **kw):
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            runtime_metrics.inc(name)

    def _on_duration(event, duration, **kw):
        name = _DURATION_SERIES.get(event)
        if name is not None:
            runtime_metrics.observe(name, duration)
            runtime_metrics.inc("compile.events")

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    _jax_listeners_installed = True
    return True


@contextlib.contextmanager
def compiled_profiler(trace_dir=None, sorted_key="total"):
    """Trace compiled execution inside the block and print the per-IR-op
    device-time table on exit (the compiled-path counterpart of
    ``op_profiler``, which times interpret mode).  A temp trace dir is
    created — and removed afterwards — unless ``trace_dir`` is given
    (pass one to keep the raw xplane protos)."""
    import shutil
    import tempfile
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="ptprof_")
    jax.profiler.start_trace(d)
    try:
        yield d
    finally:
        jax.profiler.stop_trace()
        try:
            table, _ = compiled_op_table(d, sorted_key)
            print(table)
        finally:
            if own:
                shutil.rmtree(d, ignore_errors=True)
