"""Profiler bridge (reference ``python/paddle/fluid/profiler.py`` over the
C++ host/device tracer ``paddle/fluid/platform/profiler.cc`` + CUPTI
``device_tracer.h:32``).

TPU-native realization: ``jax.profiler`` traces (viewable in
TensorBoard/XProf) carry both host and device timelines — the role CUPTI
plays on GPU.  Op-level annotation uses ``jax.named_scope`` markers inserted
by the executor; ``profiler(state, sorted_key)`` context mirrors the
reference API.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax

__all__ = ["cuda_profiler", "reset_profiler", "profiler",
           "start_profiler", "stop_profiler"]

_trace_dir = None
_start_time = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Name kept for API parity; on TPU this is an XLA/XProf trace."""
    with profiler("All", profile_path=output_file):
        yield


def reset_profiler():
    pass


def start_profiler(state="All", profile_path="/tmp/paddle_tpu_profile"):
    global _trace_dir, _start_time
    _trace_dir = profile_path
    _start_time = time.time()
    try:
        jax.profiler.start_trace(profile_path)
    except Exception:  # already tracing
        pass


def stop_profiler(sorted_key=None, profile_path=None):
    global _trace_dir
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    _trace_dir = None


@contextlib.contextmanager
def profiler(state="All", sorted_key=None,
             profile_path="/tmp/paddle_tpu_profile"):
    """reference ``profiler.py:76``."""
    start_profiler(state, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
