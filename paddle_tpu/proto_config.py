"""Config schema + parser: the TrainerConfig/ModelConfig analog.

Reference: ``proto/TrainerConfig.proto`` / ``ModelConfig.proto`` (V16) and
``python/paddle/trainer/config_parser.py:4398`` ``parse_config`` (W3) —
a Python config script runs under a capture context and produces one
serializable artifact holding the model topology + trainer settings.

TPU re-design: the Program IR already serializes (``Program.to_dict``),
so the "proto" is a versioned JSON document wrapping that dict plus the
optimizer/data-source settings the DSL's ``settings()`` /
``define_py_data_sources2()`` recorded.  ``build_programs`` reconstructs
runnable main+startup programs from a parsed config.
"""

from __future__ import annotations

import dataclasses
import json
import runpy

CONFIG_VERSION = 1

__all__ = ["TrainerConfig", "parse_config", "build_programs"]


@dataclasses.dataclass
class TrainerConfig:
    """The TrainerConfig.proto analog (model + optimizer + data)."""

    model: dict                 # Program.to_dict() of the main program
    startup: dict               # Program.to_dict() of the startup program
    settings: dict              # learning rate / method / batch size
    data_sources: dict          # define_py_data_sources2 record
    outputs: list               # output variable names
    version: int = CONFIG_VERSION

    def to_json(self, path=None, indent=None):
        doc = dataclasses.asdict(self)
        text = json.dumps(doc, indent=indent)
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    @staticmethod
    def from_json(text_or_path):
        try:
            doc = json.loads(text_or_path)
        except (json.JSONDecodeError, ValueError):
            with open(text_or_path) as f:
                doc = json.load(f)
        if doc.get("version") != CONFIG_VERSION:
            raise ValueError(
                f"config version {doc.get('version')} != {CONFIG_VERSION}")
        return TrainerConfig(**doc)


def parse_config(config, config_arg_str=None):
    """Run a config script/callable under fresh programs and capture the
    result (reference ``config_parser.py parse_config``).

    ``config``: a path to a python config file, or a zero-arg callable
    that builds the network with the trainer_config_helpers / v2 DSL and
    returns its output variable(s).
    """
    import paddle_tpu as fluid
    from paddle_tpu.trainer_config_helpers import optimizers as opt_mod
    from paddle_tpu.trainer_config_helpers import data_sources as ds_mod

    # fresh capture context: a previous parse's settings must not leak
    opt_mod._current = {}
    ds_mod._current = {}
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if callable(config):
            result = config()
        else:
            ns = runpy.run_path(config)
            result = ns.get("outputs") or ns.get("cost")
    out_vars = result if isinstance(result, (list, tuple)) else \
        ([result] if result is not None else [])
    return TrainerConfig(
        model=main.to_dict(),
        startup=startup.to_dict(),
        settings=opt_mod.current_settings(),
        data_sources=ds_mod.current_data_sources(),
        outputs=[v.name for v in out_vars if hasattr(v, "name")])


def build_programs(config: TrainerConfig):
    """Reconstruct (main, startup, output_vars) from a parsed config —
    the Executor runs these directly (the reference ships its proto to
    the C++ trainer the same way)."""
    from paddle_tpu.framework import Program

    main = Program.from_dict(config.model)
    startup = Program.from_dict(config.startup)
    outs = [main.global_block().var(n) for n in config.outputs]
    return main, startup, outs
