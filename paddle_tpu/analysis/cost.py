"""Static FLOPs/bytes cost model over the Program IR.

Per-op ``@cost.rule`` functions ride the typecheck pass's shape
inference (``analysis/typecheck.py``): :func:`estimate` propagates
shapes/dtypes from the program's trusted roots exactly like
``check_types`` and hands each cost rule the resolved
:class:`~paddle_tpu.analysis.typecheck.VarInfo` of the op's operands.
A rule returns ``(flops, bytes)``; an op type without a rule (or with
unknown shapes) contributes zero and lands on the report's
``uncovered`` list rather than guessing — the same silence-over-noise
contract the type checker holds.

The model is cross-checked against PR 12's captured XLA
``cost_analysis()`` on compiled zoo programs
(``tests/test_perf.py::TestAnalyticalFlopsCrossCheck``), so three
accountings stay mutually anchored: the bench formula
(``models/transformer.train_flops_per_token``), these per-op rules, and
XLA itself.

Three consumers:

* ``lod.select_bucket_edges`` — :func:`row_cost_fn` fits cost as a
  function of batch rows so bucket edges minimize expected padded
  FLOPs instead of defaulting to powers of two;
* ``gen.GenScheduler`` — :meth:`GenPredictor.prefill_cost` prices a
  prompt's prefill from the bundle's prefill program, and the
  scheduler's per-iteration admission budget weighs admissions by it;
* ``parallel.pipeline_transpiler`` — stage balancing cuts at quantiles
  of :func:`op_flops` instead of its private three-op analytic table.

Registering a rule for a new op::

    from paddle_tpu.analysis import cost

    @cost.rule("my_op")
    def _my_op(op, info):
        x = info(op.input("X")[0])
        n = cost.numel(x.shape)
        if n is None:
            return None          # unknown shapes -> uncovered
        return 3 * n, cost.io_bytes(op, info)
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.analysis import typecheck
from paddle_tpu.analysis.typecheck import TypeEnv, VarInfo, _UNKNOWN

__all__ = ["rule", "covered_op_types", "estimate", "op_flops",
           "numel", "io_bytes", "CostReport", "validate_cost_report",
           "row_cost_fn", "REPORT_KEYS"]

_RULES = {}

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "float32": 4, "int32": 4, "float16": 2,
    "bfloat16": 2, "int16": 2, "int8": 1, "uint8": 1, "bool": 1,
}


def rule(*op_types):
    """Decorator registering ``fn(op, info) -> (flops, bytes) | None``
    as the cost rule for one or more op types.  ``info(name)`` resolves
    a variable to its inferred :class:`VarInfo`.  Returning None (or
    raising) degrades the op to the uncovered list."""

    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn

    return deco


def covered_op_types():
    return set(_RULES)


def numel(shape, default_dim=1):
    """Element count of a static shape; unknown (-1) dims count as
    ``default_dim`` so batch-relative costs stay comparable; ``None``
    shape -> None."""
    if shape is None:
        return None
    n = 1
    for d in shape:
        n *= default_dim if d is None or d < 0 else int(d)
    return n


def _var_bytes(inf, default_dim=1):
    n = numel(inf.shape, default_dim)
    if n is None:
        return None
    return n * _DTYPE_BYTES.get(str(inf.dtype), 4)


def io_bytes(op, info, default_dim=1):
    """Bytes moved through the op's known-shape inputs and outputs —
    the default bytes estimate every rule can fall back on.  Unknown
    operands contribute zero (undercount, never a guess)."""
    total = 0
    for names in list(op.inputs.values()) + list(op.outputs.values()):
        for n in names:
            b = _var_bytes(info(n), default_dim)
            if b:
                total += b
    return total


# ---------------------------------------------------------------------------
# estimation walk (rides the typecheck rules for shape propagation)
# ---------------------------------------------------------------------------

class CostReport:
    """Per-program cost estimate: total flops/bytes, a per-op table,
    and the uncovered op-type list (coverage gap, not a claim)."""

    def __init__(self, total_flops, total_bytes, per_op, uncovered):
        self.total_flops = int(total_flops)
        self.total_bytes = int(total_bytes)
        self.per_op = list(per_op)
        self.uncovered = sorted(uncovered)

    def by_op_type(self):
        out = {}
        for row in self.per_op:
            agg = out.setdefault(row["op_type"],
                                 {"flops": 0, "bytes": 0, "count": 0})
            agg["flops"] += row["flops"]
            agg["bytes"] += row["bytes"]
            agg["count"] += 1
        return out

    def to_dict(self):
        return {"format": 1, "total_flops": self.total_flops,
                "total_bytes": self.total_bytes,
                "per_op": self.per_op, "uncovered": self.uncovered}

    def __repr__(self):
        return (f"CostReport(flops={self.total_flops:,}, "
                f"bytes={self.total_bytes:,}, "
                f"uncovered={len(self.uncovered)})")


REPORT_KEYS = ("format", "total_flops", "total_bytes", "per_op",
               "uncovered")


def validate_cost_report(obj):
    """Schema problems of a ``CostReport.to_dict()`` body (the
    selfcheck ``opt`` section's gate) as a list of strings."""
    problems = []
    if not isinstance(obj, dict):
        return [f"cost report must be an object, got "
                f"{type(obj).__name__}"]
    for k in REPORT_KEYS:
        if k not in obj:
            problems.append(f"missing key {k!r}")
    if problems:
        return problems
    if obj["format"] != 1:
        problems.append(f"format must be 1, got {obj['format']!r}")
    for k in ("total_flops", "total_bytes"):
        v = obj[k]
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{k} must be a non-negative integer")
    if not isinstance(obj["uncovered"], list):
        problems.append("uncovered must be a list")
    if not isinstance(obj["per_op"], list):
        return problems + ["per_op must be a list"]
    for i, row in enumerate(obj["per_op"]):
        where = f"per_op[{i}]"
        if not isinstance(row, dict):
            problems.append(f"{where}: must be an object")
            continue
        for k in ("op_index", "op_type", "flops", "bytes"):
            if k not in row:
                problems.append(f"{where}: missing key {k!r}")
                continue
            if k != "op_type" and (not isinstance(row[k], int)
                                   or isinstance(row[k], bool)
                                   or row[k] < 0):
                problems.append(f"{where}: {k} must be a non-negative "
                                f"integer")
    return problems


def estimate(program):
    """Walk the global block with typecheck shape propagation and price
    each op through its cost rule (unknown dims count as 1 — totals
    undercount rather than guess).  Returns a :class:`CostReport`."""
    from paddle_tpu import profiler as _profiler
    block = program.global_block()
    diags = []
    tc_uncovered = set()
    tc = TypeEnv(block, diags, tc_uncovered)
    total_flops = 0
    total_bytes = 0
    per_op = []
    uncovered = set()
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        tc.op_index = i

        def info(name, _tc=tc):
            inf = _tc.info(name)
            if inf.shape is None and name:
                # fall back to the build-time declared shape (the
                # pipeline transpiler's source of truth) when dataflow
                # could not prove one
                try:
                    v = block.var(name)
                except KeyError:
                    return inf
                if v.shape is not None:
                    return VarInfo(v.shape, v.dtype)
            return inf

        flops_bytes = None
        fn = _RULES.get(op.type)
        if fn is not None:
            try:
                flops_bytes = fn(op, info)
            except Exception:
                flops_bytes = None
        if flops_bytes is None:
            uncovered.add(op.type)
            flops, nbytes = 0, 0
        else:
            flops, nbytes = flops_bytes
            flops = max(int(flops), 0)
            nbytes = max(int(nbytes), 0)
        per_op.append({"op_index": i, "op_type": op.type,
                       "flops": flops, "bytes": nbytes})
        total_flops += flops
        total_bytes += nbytes
        # propagate shapes through the typecheck rule so downstream
        # cost rules see resolved operand shapes
        tfn = typecheck._RULES.get(op.type)
        if tfn is None:
            for n in op.output_arg_names:
                tc.set(n)
        else:
            try:
                tfn(op, tc)
            except Exception:
                for n in op.output_arg_names:
                    tc.set(n)
    _profiler.runtime_metrics.inc("cost.estimates")
    return CostReport(total_flops, total_bytes, per_op, uncovered)


def op_flops(op, block, default=None):
    """FLOPs of one op priced from the BLOCK's declared var shapes (the
    build-time ``infer_shape`` metadata) — the pipeline transpiler's
    stage-balancing weight.  Falls back to ``default`` (or 0) when the
    op has no rule or unknown shapes."""

    def info(name):
        if not name:
            return _UNKNOWN
        try:
            v = block.var(name)
        except KeyError:
            return _UNKNOWN
        return VarInfo(v.shape, v.dtype) if v.shape is not None \
            else _UNKNOWN

    fn = _RULES.get(op.type)
    if fn is None:
        return default
    try:
        out = fn(op, info)
    except Exception:
        return default
    if out is None:
        return default
    return max(int(out[0]), 0)


def row_cost_fn(program, batch_var=None, dim=0, probe_rows=(8, 16)):
    """Fit ``flops(size)`` as an affine function of dim ``dim`` of
    ``batch_var`` (default: the program's first ``is_data`` var):
    estimate the program at two sizes and interpolate.  The returned
    callable prices a padded bucket for
    ``lod.select_bucket_edges`` — batch-size buckets probe the row
    dim, the gen prefill's prompt buckets probe the length dim."""
    block = program.global_block()
    if batch_var is None:
        for v in block.vars.values():
            if getattr(v, "is_data", False):
                batch_var = v.name
                break
    if batch_var is None:
        return lambda rows: float(rows)
    var = block.var(batch_var)
    saved = var.shape
    points = []
    try:
        for rows in probe_rows:
            shape = list(saved or (-1,))
            shape[dim] = int(rows)
            var.shape = tuple(shape)
            points.append((rows, estimate(program).total_flops))
    finally:
        var.shape = saved
    (r0, f0), (r1, f1) = points
    if r1 == r0 or f1 <= f0:
        return lambda rows: float(max(f0, 1)) * rows / max(r0, 1)
    slope = (f1 - f0) / (r1 - r0)
    const = f0 - slope * r0

    def fn(rows):
        return max(const + slope * rows, 0.0)

    return fn


# ---------------------------------------------------------------------------
# rules — the compute-dominant families first (matmul/conv), then the
# per-element families, mirroring the typecheck rule layout
# ---------------------------------------------------------------------------

def _shape(info, op, slot):
    names = op.input(slot)
    return info(names[0]).shape if names else None


@rule("mul")
def _c_mul(op, info):
    x = info(op.input("X")[0]) if op.input("X") else _UNKNOWN
    y = info(op.input("Y")[0]) if op.input("Y") else _UNKNOWN
    if x.shape is None or y.shape is None:
        return None
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    m = numel(x.shape[:xn])
    k = numel(x.shape[xn:])
    n = numel(y.shape[yn:])
    if None in (m, k, n):
        return None
    return 2 * m * k * n, io_bytes(op, info)


@rule("matmul")
def _c_matmul(op, info):
    x = info(op.input("X")[0]) if op.input("X") else _UNKNOWN
    y = info(op.input("Y")[0]) if op.input("Y") else _UNKNOWN
    if x.shape is None or y.shape is None or len(x.shape) < 2 or \
            len(y.shape) < 2:
        return None
    xs, ys = list(x.shape), list(y.shape)
    if op.attr("transpose_X", False):
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if op.attr("transpose_Y", False):
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = numel(xs[:-2]) if len(xs) >= len(ys) else numel(ys[:-2])
    m, k, n = xs[-2], xs[-1], ys[-1]
    if any(d is None or d < 0 for d in (m, k, n)) or batch is None:
        return None
    return 2 * batch * m * k * n, io_bytes(op, info)


# grads of a dot: dX = dOut @ Y^T and dY = X^T @ dOut — two dots of the
# forward's geometry, so 2x the forward FLOPs (the standard 2N fwd / 4N
# bwd split behind the bench's 6N accounting)
@rule("mul_grad")
def _c_mul_grad(op, info):
    fwd = _c_mul(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))


@rule("matmul_grad")
def _c_matmul_grad(op, info):
    fwd = _c_matmul(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))


@rule("conv2d", "depthwise_conv2d")
def _c_conv2d(op, info):
    w = info(op.input("Filter")[0]) if op.input("Filter") else _UNKNOWN
    # on the _grad op the forward's Output arrives as an INPUT slot
    outs = op.output("Output") or op.input("Output")
    o = info(outs[0]) if outs else _UNKNOWN
    if w.shape is None or o.shape is None or len(w.shape) != 4 or \
            len(o.shape) != 4:
        return None
    co, ci, kh, kw = w.shape
    n, _, ho, wo = o.shape
    if any(d < 0 for d in (co, ci, kh, kw, ho, wo)):
        return None
    n = 1 if n < 0 else n
    return 2 * n * ho * wo * co * ci * kh * kw, io_bytes(op, info)


@rule("conv2d_grad", "depthwise_conv2d_grad")
def _c_conv2d_grad(op, info):
    fwd = _c_conv2d(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))


@rule("scaled_dot_product_attention")
def _c_sdpa(op, info):
    q = info(op.input("Q")[0]) if op.input("Q") else _UNKNOWN
    if q.shape is None or len(q.shape) != 4:
        return None
    b, h, s, d = q.shape
    if any(x < 0 for x in (h, s, d)):
        return None
    b = 1 if b < 0 else b
    return 4 * b * h * s * s * d, io_bytes(op, info)


@rule("paged_attention")
def _c_paged_attention(op, info):
    """Paged decode attention prices the pages ACTUALLY addressed by
    the step's page-table feed ([S, P] -> S*P*page_len token rows of
    K and V), not the full pool — the whole point of the layout; a
    full-pool ``io_bytes`` would price every bucket identically and
    hide the occupancy win from ``row_cost_fn``/``gen.decode_mfu``."""
    q = info(op.input("Q")[0]) if op.input("Q") else _UNKNOWN
    kc = info(op.input("KCache")[0]) if op.input("KCache") else _UNKNOWN
    pt = info(op.input("PageTable")[0]) if op.input("PageTable") \
        else _UNKNOWN
    if q.shape is None or kc.shape is None or pt.shape is None or \
            len(kc.shape) != 3 or len(pt.shape) != 2:
        return None
    hd, pl, p = kc.shape[-1], kc.shape[1], pt.shape[1]
    if any(x < 0 for x in (hd, pl, p)):
        return None
    s = q.shape[0] if q.shape[0] > 0 else 1
    t = p * pl
    item = _DTYPE_BYTES.get(str(kc.dtype), 4)
    flops = 4 * s * t * hd                       # QK^T + PV per head-row
    bytes_ = (2 * s * t * hd          # K/V pages gathered
              + 4 * s * hd            # q, k, v rows in + out
              + 2 * s * hd) * item    # tail-page scatter write (k + v)
    return flops, bytes_


def _per_element(mult):
    def fn(op, info):
        n = None
        for slot in ("X", "Logits", "Out"):
            names = op.input(slot)
            if names:
                n = numel(info(names[0]).shape)
                break
        if n is None:
            # grad ops / odd slot names: the largest known operand
            # (grads mirror their primal's geometry)
            for name in op.input_arg_names:
                m = numel(info(name).shape)
                if m is not None:
                    n = m if n is None else max(n, m)
        if n is None:
            return None
        return mult * n, io_bytes(op, info)

    return fn


#: cheap elementwise families: ~1 FLOP per element
_ELEMENTWISE_1X = (
    "relu", "abs", "square", "scale", "clip", "floor", "ceil", "round",
    "cast", "assign", "fill_zeros_like", "elementwise_add",
    "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "dropout", "label_smooth",
    "sum", "mean", "increment", "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "sequence_pool", "sequence_expand", "top_k",
    "accuracy", "transpose", "transpose2", "reshape", "reshape2",
    "concat", "lod_reset",
)

#: transcendental elementwise families: ~10 FLOPs per element (exp/log/
#: div chains — the conventional softmax/activation accounting)
_ELEMENTWISE_10X = (
    "sigmoid", "tanh", "exp", "log", "sqrt", "softsign", "softplus",
    "relu6", "leaky_relu", "elu", "gelu", "hard_sigmoid", "swish",
    "brelu", "pow", "reciprocal", "sin", "cos", "softmax",
    "sequence_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "batch_norm",
)

rule(*_ELEMENTWISE_1X)(_per_element(1))
rule(*_ELEMENTWISE_10X)(_per_element(10))

# the per-element families' grads move ~the same element counts
rule(*[t + "_grad" for t in _ELEMENTWISE_1X
       if t not in ("less_than", "less_equal", "greater_than",
                    "greater_equal", "equal", "not_equal", "accuracy",
                    "increment", "assign")])(_per_element(2))
rule(*[t + "_grad" for t in _ELEMENTWISE_10X])(_per_element(10))


@rule("lookup_table")
def _c_lookup_table(op, info):
    ids = info(op.input("Ids")[0]) if op.input("Ids") else _UNKNOWN
    w = info(op.input("W")[0]) if op.input("W") else _UNKNOWN
    n = numel(ids.shape)
    if n is None or w.shape is None or len(w.shape) != 2:
        return None
    width = w.shape[1]
    if width < 0:
        return None
    # a gather: no FLOPs, ids*width elements moved
    return 0, n * width * _DTYPE_BYTES.get(str(w.dtype), 4)


@rule("lookup_table_grad")
def _c_lookup_table_grad(op, info):
    fwd = _c_lookup_table(op, info)
    if fwd is None:
        return None
    # scatter-add back into the table: one add per gathered element
    return fwd[1] // 4, 2 * fwd[1]


@rule("merge_selected_rows")
def _c_merge_selected_rows(op, info):
    x = info(op.input("X")[0]) if op.input("X") else _UNKNOWN
    n = numel(x.shape)
    if n is None:
        return None
    # sort rows + segment-sum the values: one add per element, values
    # read once and written once (the static-shape merge keeps the full
    # row set, so the logical [height, dim] numel is the honest bound)
    item = _DTYPE_BYTES.get(str(x.dtype), 4)
    return n, 2 * n * item


@rule("get_tensor_from_selected_rows")
def _c_get_tensor_from_selected_rows(op, info):
    x = info(op.input("X")[0]) if op.input("X") else _UNKNOWN
    n = numel(x.shape)
    if n is None:
        return None
    # scatter-add into a zeroed [height, dim] tensor
    item = _DTYPE_BYTES.get(str(x.dtype), 4)
    return n, 2 * n * item


@rule("split_ids")
def _c_split_ids(op, info):
    ids = info(op.input("Ids")[0]) if op.input("Ids") else _UNKNOWN
    n = numel(ids.shape)
    if n is None:
        return None
    shards = max(len(op.output("Out")), 1)
    # one mod-compare per (id, shard) pair; padded outputs move n ids
    # per shard
    item = _DTYPE_BYTES.get(str(ids.dtype), 8)
    return n * shards, (1 + shards) * n * item


@rule("split_selected_rows")
def _c_split_selected_rows(op, info):
    x = info(op.input("X")[0]) if op.input("X") else _UNKNOWN
    n = numel(x.shape)
    if n is None:
        return None
    shards = max(len(op.output("Out")), 1)
    item = _DTYPE_BYTES.get(str(x.dtype), 4)
    return n * shards, (1 + shards) * n * item


@rule("nce")
def _c_nce(op, info):
    x = info(op.input("Input")[0]) if op.input("Input") else _UNKNOWN
    label = info(op.input("Label")[0]) if op.input("Label") else _UNKNOWN
    if x.shape is None or len(x.shape) != 2:
        return None
    rows, d = x.shape
    rows = rows if rows >= 0 else 1
    if d < 0:
        return None
    num_true = (label.shape[1] if label.shape is not None and
                len(label.shape) == 2 else 1)
    s = num_true + int(op.attr("num_neg_samples", 10))
    # per (row, sample): a D-dot + ~10-FLOP sigmoid/log chain
    return rows * s * (2 * d + 10), io_bytes(op, info)


@rule("nce_grad")
def _c_nce_grad(op, info):
    fwd = _c_nce(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))


@rule("fill_constant", "fill", "fill_constant_batch_size_like",
      "assign_value", "uniform_random", "gaussian_random",
      "shape", "max_sequence_len", "lod_rank_table")
def _c_fill(op, info):
    outs = op.output("Out")
    o = info(outs[0]) if outs else _UNKNOWN
    n = numel(o.shape)
    if n is None:
        n = numel(op.attr("shape")) or 0
    return 0, n * _DTYPE_BYTES.get(str(o.dtype), 4)


@rule("sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
      "decayed_adagrad", "rmsprop", "ftrl", "lars_momentum")
def _c_optimizer(op, info):
    p = info(op.input("Param")[0]) if op.input("Param") else _UNKNOWN
    n = numel(p.shape)
    if n is None:
        return None
    # Adam-class updates: ~10 FLOPs per parameter (two moment EMAs,
    # bias correction, the update itself); SGD-class overcounts
    # harmlessly (the step is bandwidth-bound either way)
    return 10 * n, io_bytes(op, info)


@rule("pool2d")
def _c_pool2d(op, info):
    outs = op.output("Out") or op.input("Out")
    o = info(outs[0]) if outs else _UNKNOWN
    n = numel(o.shape)
    if n is None:
        return None
    k = op.attr("ksize", [1, 1])
    kk = int(np.prod(k)) if isinstance(k, (list, tuple)) else int(k) ** 2
    return n * max(kk, 1), io_bytes(op, info)


@rule("pool2d_grad")
def _c_pool2d_grad(op, info):
    fwd = _c_pool2d(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))


@rule("lstm")
def _c_lstm(op, info):
    x = info(op.input("Input")[0]) if op.input("Input") else _UNKNOWN
    w = info(op.input("Weight")[0]) if op.input("Weight") else _UNKNOWN
    if x.shape is None or w.shape is None or len(w.shape) != 2:
        return None
    rows = x.shape[0] if x.shape[0] >= 0 else 1
    hidden = w.shape[0]
    if hidden < 0:
        return None
    # per row: input projection rides a separate mul op; here the
    # recurrent 4H x H dot + gate activations
    return rows * (2 * hidden * 4 * hidden + 40 * hidden), \
        io_bytes(op, info)


@rule("lstm_grad")
def _c_lstm_grad(op, info):
    fwd = _c_lstm(op, info)
    return None if fwd is None else (2 * fwd[0], io_bytes(op, info))
