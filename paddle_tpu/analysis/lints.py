"""Graph lints: dead ops, unused feeds, donation/aliasing hazards.

These are warnings, not errors — the program runs, but some of it is
wasted work (dead ops compile and execute for nothing) or quietly
dangerous (a donated buffer read after its in-place update poisons the
sentinel's skip-step discard, PR 5).  The zero-false-positive contract
applies: an op with ANY effect besides its dataflow outputs (host ops,
sub-blocks, persistable writes, declared stateful/aliasing outputs,
RNG, readers/CSP/persistence) is never called dead.
"""

from __future__ import annotations

from paddle_tpu.analysis.diagnostics import Diagnostic
from paddle_tpu.analysis.structural import _external_reads, _sub_blocks

# op effect classification lives in the SHARED registry
# (analysis/opmeta.py) so this lint's exemptions, the opt passes'
# removal guards, and the cost model can never drift apart — the
# scanner test (tests/test_opmeta.py) enforces single ownership
from paddle_tpu.analysis.opmeta import has_effects as _has_effects

__all__ = ["check_graph"]


def check_graph(program, feed_names=None, fetch_names=None):
    diags = []
    block = program.global_block()
    from paddle_tpu.ops import registry

    persistable = {v.name for blk in program.blocks
                   for v in blk.vars.values()
                   if getattr(v, "persistable", False)}

    # ---- dead ops (PTA007): reverse liveness sweep, prune()-style ----
    needed = set(fetch_names or ())
    needed |= persistable  # a persistable write IS an effect
    live = [False] * len(block.ops)
    for i in range(len(block.ops) - 1, -1, -1):
        op = block.ops[i]
        outs = [n for n in op.output_arg_names if n]
        if _has_effects(op, registry) or any(n in needed for n in outs):
            live[i] = True
            needed.update(n for n in op.input_arg_names if n)
            for sub in _sub_blocks(op):
                needed.update(_external_reads(sub))
    for i, op in enumerate(block.ops):
        if live[i]:
            continue
        outs = sorted({n for n in op.output_arg_names if n})
        if outs and all("@GRAD" in n for n in outs):
            # autodiff artifacts: append_backward emits grad chains for
            # every path even when only the param grads are consumed,
            # callers fetch arbitrary grad vars ad hoc (calc_gradient,
            # OpTest), and XLA DCE elides the unused ones at compile —
            # flagging them would be all noise, so the dead-op lint
            # covers user/transpiler-authored ops only
            continue
        diags.append(Diagnostic(
            "PTA007",
            f"op `{op.type}` at op #{i} is dead: its output(s) "
            f"{outs} are never consumed by a later op, never fetched, "
            f"and not persistable — it compiles and runs for nothing",
            block_idx=block.idx, op_index=i, op_type=op.type,
            var=outs[0] if outs else None,
            site=getattr(op, "creation_site", None)))

    # ---- unused feeds (PTA008) ----
    reads = set()
    for blk in program.blocks:
        for op in blk.ops:
            reads.update(n for n in op.input_arg_names if n)
    if feed_names is not None:
        feeds = list(feed_names)
    else:
        feeds = [v.name for v in block.vars.values()
                 if getattr(v, "is_data", False)]
        if not any(n in reads for n in feeds):
            # a program that reads NO feed at all is not a step program
            # (startup/init programs carry mirrored data vars for parity)
            feeds = []
    for name in feeds:
        if name not in reads and name not in (fetch_names or ()):
            diags.append(Diagnostic(
                "PTA008",
                f"feed `{name}` is declared but no op reads it — "
                f"dropping it from the feed list saves a host->device "
                f"transfer per step",
                block_idx=block.idx, var=name))

    # ---- donation/aliasing hazards (PTA009) ----
    # An op whose opdef declares stateful_outputs updates those vars
    # IN PLACE (the executor donates their buffers across steps).  Any
    # later op reading such a var observes the post-update value — and
    # a sentinel skip-step (which discards the update) cannot give that
    # reader back the pre-step state it already consumed.
    donated_at = {}  # var name -> (op index, op type) of the donating op
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names:
            if n in donated_at:
                j, jtype = donated_at[n]
                diags.append(Diagnostic(
                    "PTA009",
                    f"op `{op.type}` at op #{i} reads `{n}` after op "
                    f"#{j} (`{jtype}`) updated it in place — under "
                    f"buffer donation the reader sees the post-update "
                    f"buffer, and a sentinel skip-step discard cannot "
                    f"restore the value it consumed; read the var "
                    f"before the update, or fetch it instead",
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=n, site=getattr(op, "creation_site", None)))
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.stateful_outputs:
            for slot in opdef.stateful_outputs:
                for n in op.output(slot):
                    if n:
                        donated_at[n] = (i, op.type)

    return diags
