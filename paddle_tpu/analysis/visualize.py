"""Program visualization: GraphViz DOT rendering + pseudo-code pretty
printing (reference ``python/paddle/fluid/debuger.py`` +
``graphviz.py`` + ``net_drawer.py``; grown out of the vestigial
``paddle_tpu/debuger.py``, which remains as a deprecation shim).

:func:`program_dot` renders a whole Program — every block as a
clustered subgraph, ops as boxes, vars as ellipses, gradients
highlighted — annotated with the analysis facts the repo already
computes: each op's ``creation_site`` as a node tooltip, and the
donation plan (``memory_optimization_transpiler.plan_donation``
attaches ``program._donation_plan``) as per-var feed-donation /
in-place-update decorations.  Exposed as ``paddle_tpu lint <model>
--dot out.dot`` — render with any dot tool; no binary needed to
produce the file.
"""

from __future__ import annotations

__all__ = ["program_dot", "draw_block_graphviz", "pprint_program_codes",
           "pprint_block_codes"]

from paddle_tpu.ops.registry import GRAD_SUFFIX


def _var_label(block, name):
    try:
        v = block.var(name)
        shape = "x".join(str(d) for d in (v.shape or ())) or "?"
        return f"{name}\\n{v.dtype}[{shape}]"
    except KeyError:
        return name


def _esc(text):
    return str(text).replace("\\", "\\\\").replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a .dot graph of one block (reference ``debuger.py``
    draw_block_graphviz).  Returns the dot source text."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    _emit_block(lines, block, highlights, donation=None, cluster=False)
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def _emit_block(lines, block, highlights, donation, cluster=True,
                indent="  "):
    """One block's nodes/edges; sub-block attrs recurse as nested
    clusters (control-flow ops own their body blocks)."""
    from paddle_tpu import framework
    donated_feeds = set()
    inplace = {}
    if donation:
        donated_feeds = set(donation.get("donatable_feeds") or ())
        inplace = donation.get("inplace_updates") or {}
    seen_vars = set()
    prefix = f"b{block.idx}_"

    def var_node(name):
        nid = (prefix + f"var_{name}").replace(".", "_") \
            .replace("@", "_AT_")
        if name not in seen_vars:
            seen_vars.add(name)
            color = "orange" if name.endswith(GRAD_SUFFIX) else \
                ("red" if name in highlights else "lightblue")
            label = _var_label(block, name)
            extra = ""
            if name in donated_feeds:
                label += "\\n[donated feed]"
                extra = ", peripheries=2"
            elif name in inplace:
                upd = inplace[name]
                label += (f"\\n[in-place @ op {upd['op_index']} "
                          f"{upd['op_type']}]")
                extra = ", peripheries=2"
            lines.append(
                f'{indent}"{nid}" [label="{label}", '
                f'shape=ellipse, style=filled, fillcolor={color}'
                f'{extra}];')
        return nid

    for i, op in enumerate(block.ops):
        op_id = f"{prefix}op_{i}_{op.type}"
        tooltip = ""
        site = getattr(op, "creation_site", None)
        if site:
            tooltip = f', tooltip="{_esc(site[0])}:{site[1]}"'
        lines.append(f'{indent}"{op_id}" [label="{op.type}", shape=box, '
                     f'style=filled, fillcolor=palegreen{tooltip}];')
        for n in op.input_arg_names:
            if n:
                lines.append(f'{indent}"{var_node(n)}" -> "{op_id}";')
        for n in op.output_arg_names:
            if n:
                lines.append(f'{indent}"{op_id}" -> "{var_node(n)}";')
        for key, attr in sorted(op.attrs.items()):
            if isinstance(attr, framework.Block):
                lines.append(f'{indent}subgraph cluster_b{attr.idx} {{')
                lines.append(f'{indent}  label="block {attr.idx} '
                             f'({op.type}.{key})"; style=dashed;')
                _emit_block(lines, attr, highlights, donation=None,
                            indent=indent + "  ")
                lines.append(f"{indent}}}")
                lines.append(f'{indent}"{op_id}" -> '
                             f'"b{attr.idx}_anchor" [style=dotted];')
    if cluster:
        # an invisible anchor lets a parent op point at this cluster
        lines.append(f'{indent}"{prefix[:-1]}_anchor" '
                     f'[shape=point, style=invis];')


def program_dot(program, highlights=None, path=None):
    """DOT source of a whole Program: the global block at top level,
    every sub-block as a dashed cluster under its owning control-flow
    op, donation-plan annotations when the program was planned
    (``plan_donation``), and op ``creation_site`` tooltips.  Writes to
    ``path`` when given; returns the text either way."""
    plan = getattr(program, "_donation_plan", None)
    donation = plan.to_dict() if plan is not None else None
    lines = ["digraph Program {", "  rankdir=TB;",
             '  labelloc=t; label="paddle_tpu Program";']
    if donation and donation.get("dropped"):
        notes = "\\n".join(
            f"{d['var']}: {d['reason']}"
            for d in donation["dropped"][:8])
        lines.append(f'  "donation_dropped" [shape=note, '
                     f'label="not donatable:\\n{_esc(notes)}"];')
    _emit_block(lines, program.global_block(), set(highlights or ()),
                donation=donation, cluster=False)
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def pprint_block_codes(block, show_backward=True):
    """Pseudo-code rendering of one block (reference ``debuger.py``
    pprint_block_codes)."""
    out = []
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        outs = ", ".join(n for ns in op.outputs.values() for n in ns if n)
        ins = ", ".join(n for ns in op.inputs.values() for n in ns if n)
        attrs = ", ".join(
            f"{k}={v!r}" for k, v in sorted(op.attrs.items())
            if not hasattr(v, "ops"))  # skip sub-blocks
        call = f"{op.type}({ins}"
        if attrs:
            call += f", {attrs}"
        call += ")"
        out.append(f"{outs or '_'} = {call}" if outs else call)
    return "\n".join(out)


def pprint_program_codes(program, show_backward=True):
    chunks = []
    for blk in program.blocks:
        chunks.append(f"# block {blk.idx}")
        chunks.append(pprint_block_codes(blk, show_backward))
    return "\n".join(chunks)
