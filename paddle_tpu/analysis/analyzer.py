"""Analyzer entry points: compose the passes, gate the executor hook,
verify transpiler output.

Three call sites, three shapes:

* ``lint_program`` — everything (structure + types + graph lints), for
  ``paddle_tpu lint`` and the model-zoo gate.  Returns an
  :class:`AnalysisResult`; never raises.
* ``verify_program`` — the structural pass only; raises
  :class:`ProgramVerificationError` on error-severity findings.  This
  is what ``PADDLE_TPU_VERIFY=1`` runs in ``Executor.run`` /
  ``ParallelExecutor`` before first compile (memoized per program
  version — a cached step pays one set lookup).
* ``verify_transpiled`` — ``verify_program`` with a ``where=`` tag,
  called by every program rewriter (``backward.append_backward``, the
  parallel/pipeline/memory-optimization transpilers) so a rewrite that
  emits an ill-formed program fails AT THE REWRITE with the pass named,
  not three layers later inside an XLA trace.
"""

from __future__ import annotations

from paddle_tpu.analysis import lints, structural, typecheck
from paddle_tpu.analysis.diagnostics import (Diagnostic,
                                             ProgramVerificationError,
                                             format_diagnostics)

__all__ = ["AnalysisResult", "analyze_program", "lint_program",
           "verify_program", "verify_transpiled",
           "check_pipeline_carriers"]


class AnalysisResult:
    """Findings of one analyzer run over a program."""

    def __init__(self, diagnostics, uncovered_op_types=()):
        self.diagnostics = list(diagnostics)
        #: the warn-list: op types with no registered inference rule —
        #: shapes/dtypes were not propagated through them (coverage gap,
        #: not a defect)
        self.uncovered_op_types = sorted(uncovered_op_types)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == "warning"]

    def codes(self):
        return sorted({d.code for d in self.diagnostics})

    def format(self):
        return format_diagnostics(self.diagnostics)

    def raise_on_errors(self, where="verify_program"):
        if self.errors:
            raise ProgramVerificationError(self.diagnostics, where=where)
        return self

    def __repr__(self):
        return (f"AnalysisResult(errors={len(self.errors)}, "
                f"warnings={len(self.warnings)}, "
                f"uncovered={len(self.uncovered_op_types)})")


def analyze_program(program, feed_names=None, fetch_names=None,
                    passes=("structure", "types", "lints")):
    """Run the selected passes; returns an :class:`AnalysisResult`."""
    diags = []
    uncovered = set()
    if "structure" in passes:
        diags.extend(structural.check_structure(
            program, feed_names=feed_names, fetch_names=fetch_names))
    if "types" in passes:
        tdiags, uncovered = typecheck.check_types(program)
        diags.extend(tdiags)
    if "lints" in passes:
        diags.extend(lints.check_graph(program, feed_names=feed_names,
                                       fetch_names=fetch_names))
    order = {"error": 0, "warning": 1}
    diags.sort(key=lambda d: (order[d.severity], d.code,
                              d.op_index if d.op_index is not None else -1))
    return AnalysisResult(diags, uncovered)


def lint_program(program, feed_names=None, fetch_names=None):
    """All passes — what ``paddle_tpu lint`` and the zoo gate run."""
    return analyze_program(program, feed_names=feed_names,
                           fetch_names=fetch_names)


def verify_program(program, feed_names=None, fetch_names=None,
                   where="verify_program"):
    """Structural verification; raises ProgramVerificationError on
    errors.  Returns the AnalysisResult when clean."""
    result = analyze_program(program, feed_names=feed_names,
                             fetch_names=fetch_names,
                             passes=("structure",))
    return result.raise_on_errors(where=where)


def verify_transpiled(program, where):
    """Post-rewrite contract check for transpilers: a pass that emits a
    structurally broken program must fail HERE, naming itself."""
    return verify_program(program, where=where)


def check_pipeline_carriers(block, boundaries, where="pipeline_transpiler"):
    """Static half of the pipeline i32-carrier contract (the runtime
    half is ``_Layout.pack``'s range guard): an int64 var crossing a
    stage boundary rides the i32 lane, so a boundary value PROVABLY
    outside int32 range — an int64 ``fill_constant`` literal feeding
    the carrier — is rejected at transpile time (PTA010) instead of
    wrapping (or raising) step-side."""
    diags = []
    const_int64 = {}  # var name -> literal value(s)
    for i, op in enumerate(block.ops):
        if op.type in ("fill_constant", "fill") and \
                op.attr("dtype") == "int64":
            for n in op.output("Out"):
                const_int64[n] = (i, op.attr("value", 0))
    crossing = {n for names in boundaries for n in names}
    for n in sorted(crossing & set(const_int64)):
        i, value = const_int64[n]
        try:
            fits = typecheck.int64_fits_i32_lane(value)
        except (TypeError, ValueError):
            continue
        if not fits:
            diags.append(Diagnostic(
                "PTA010",
                f"`{n}` (int64 constant from op #{i}) crosses a "
                f"pipeline stage boundary, but its value is outside "
                f"int32 range — the i32 carrier lane cannot carry it "
                f"exactly",
                block_idx=block.idx, op_index=i,
                op_type=block.ops[i].type, var=n,
                site=getattr(block.ops[i], "creation_site", None)))
    if diags:
        raise ProgramVerificationError(diags, where=where)
    return diags
