"""``paddle_tpu selfcheck`` — every static gate in one exit-coded pass.

CI and humans need ONE command that answers "is the static story
green?": the model zoo lints clean (single-program AND as the
transpiled families the distributed verifier covers), every
scanner-enforced registry — diagnostic codes, metric names, chaos
failpoints — agrees with its documentation table, the SLO spec schema
validates (example + any armed ``PADDLE_TPU_SLO`` file), the autoscaler
policy schema validates (example + any armed ``PADDLE_TPU_AUTOSCALE``
file), and the bench trajectory's schema is intact
(``bench check --dry``).  The pytest suite
enforces the same invariants test-by-test; this module re-runs them as
a deployable command (no pytest, no tests/ checkout needed) so drift
fails a release gate, not a 3am dashboard hunt.

Each section returns ``{"name", "ok", "detail", "failures": [...]}``;
the report is ``{"ok": all-green, "sections": [...]}``.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile

import paddle_tpu

__all__ = ["run_selfcheck"]

SRC_ROOT = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
DOCS_DIR = os.path.join(os.path.dirname(SRC_ROOT), "docs")

# the same scanner regexes the registry tests use (kept in lockstep by
# tests/test_selfcheck.py's agreement checks)
_CODE = re.compile(r"\bPTA\d{3}\b")
_DOC_CODE = re.compile(r"^\|\s*`(PTA\d{3})`\s*\|", re.M)
_METRIC_LITERAL = re.compile(
    r"\.(?:inc|observe|bucket|set_gauge)\(\s*[\"']([a-zA-Z0-9_.]+)[\"']")
_METRIC_LATENCY = re.compile(r"record_latency\(\s*[\"']([a-zA-Z0-9_.]+)[\"']")
_METRIC_STAGE = re.compile(
    r"\.(?:inc|observe|bucket|set_gauge)\(\s*\n?\s*self\._metrics\s*\+"
    r"\s*[\"']\.([a-zA-Z0-9_]+)[\"']")
_METRIC_MIRROR = re.compile(
    r"[\"']((?:compile|compile_cache)\.[a-zA-Z0-9_.]+)[\"']")
_DOC_METRIC = re.compile(r"^\|\s*`([a-zA-Z0-9_.<>]+)`\s*\|", re.M)
_FIRE = re.compile(
    r"\b_?chaos\.fire\(\s*\n?\s*[\"']"
    r"([a-z0-9_]+(?:\.[a-z0-9_]+)+)[\"']")
_DOC_FAILPOINT = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|", re.M)


def _iter_sources():
    for dirpath, _, names in os.walk(SRC_ROOT):
        for n in sorted(names):
            if n.endswith(".py"):
                with open(os.path.join(dirpath, n)) as f:
                    yield os.path.join(dirpath, n), f.read()


def _read_doc(name):
    with open(os.path.join(DOCS_DIR, name)) as f:
        return f.read()


def _section(name, detail, failures):
    return {"name": name, "ok": not failures, "detail": detail,
            "failures": list(failures)}


# ---------------------------------------------------------------------------
# zoo gates
# ---------------------------------------------------------------------------

def _check_zoo_lint():
    """Strict single-program lint: zero errors AND zero warnings across
    every zoo model's forward+backward and startup programs."""
    from paddle_tpu import analysis
    from paddle_tpu.models import ZOO_MODELS, build_train_program

    failures = []
    for name in ZOO_MODELS:
        main, startup, feeds, fetches = build_train_program(name)
        for label, prog, fd, ft in ((name, main, feeds, fetches),
                                    (f"{name}/startup", startup, None,
                                     None)):
            r = analysis.lint_program(prog, feed_names=fd, fetch_names=ft)
            for d in r.diagnostics:
                failures.append(f"[{label}] {d.severity}[{d.code}]: "
                                f"{d.message}")
    return _section("zoo-lint",
                    f"{len(ZOO_MODELS)} models, strict (warnings fail)",
                    failures)


def _check_zoo_distribute():
    """Every zoo model's DistributeTranspiler plan (sharded params over
    2 shards) verifies clean."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis import ProgramVerificationError
    from paddle_tpu.models import ZOO_MODELS, build_train_program
    from paddle_tpu.parallel.distribute_transpiler import \
        DistributeTranspiler

    failures = []
    for name in ZOO_MODELS:
        main, startup, _feeds, _fetches = build_train_program(name)
        t = DistributeTranspiler()
        try:
            t.transpile(program=main, startup_program=startup,
                        pservers="a:1,b:2", shard_params=True)
        except ProgramVerificationError as e:
            failures.append(f"[{name}] {e.args[0].splitlines()[0]}")
            continue
        diags = analysis.check_distributed_spec(main, t.spec)
        for d in diags:
            failures.append(f"[{name}] {d.severity}[{d.code}]: "
                            f"{d.message}")
    return _section("zoo-distribute",
                    "DistributeTranspiler plan verification, 2 shards",
                    failures)


def _check_zoo_pipeline():
    """Every splittable zoo model's 2-stage pipeline split verifies
    clean (models whose split is rejected outright — a tensor_array
    crossing a cut — are skipped, as the transpiler itself refuses
    them with a recipe)."""
    from paddle_tpu import analysis
    from paddle_tpu.models import ZOO_MODELS, build_train_program

    failures = []
    skipped = []
    for name in ZOO_MODELS:
        main, _startup, feeds, fetches = build_train_program(name)
        if feeds is None:
            feeds = [v.name
                     for v in main.global_block().vars.values()
                     if getattr(v, "is_data", False)]
        try:
            r = analysis.lint_pipeline(main, 2, feeds, fetches)
        except ValueError:
            skipped.append(name)
            continue
        for d in r.diagnostics:
            failures.append(f"[{name}] {d.severity}[{d.code}]: "
                            f"{d.message}")
    detail = "2-stage split verification"
    if skipped:
        detail += f" (unsplittable, skipped: {', '.join(skipped)})"
    return _section("zoo-pipeline", detail, failures)


def _check_gen_bundle():
    """A freshly exported generation bundle (prefill/decode/meta) lints
    clean in multi-program mode."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis import ProgramVerificationError
    from paddle_tpu.models import gen_lm

    failures = []
    hp = gen_lm.GenConfig()
    hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
    hp.n_head = hp.n_layer = 2
    hp.d_head, hp.max_len = 8, 16
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_selfcheck_gen_")
    try:
        try:
            gen_lm.export_gen_model(tmp, hp, num_slots=2)
        except ProgramVerificationError as e:
            failures.append(e.args[0].splitlines()[0])
        else:
            for label, r in analysis.lint_gen_bundle(tmp):
                for d in r.diagnostics:
                    failures.append(f"[{label}] {d.severity}[{d.code}]: "
                                    f"{d.message}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return _section("gen-bundle",
                    "export + multi-program lint of prefill/decode",
                    failures)


def _check_paged_kv():
    """Paged-KV gate: a fresh paged gen export carries complete
    page-bucket meta, the paged decode program lints clean, and the
    static cost model prices the decode step proportionally to the fed
    page count — the occupancy-proportional read contract
    ``bench_paged.py`` times."""
    import json

    from paddle_tpu import analysis
    from paddle_tpu.analysis import cost
    from paddle_tpu.analysis.distributed import load_saved_program
    from paddle_tpu.models import gen_lm

    failures = []
    hp = gen_lm.GenConfig()
    hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
    hp.n_head, hp.n_layer = 2, 1   # one layer proves the page contract
    hp.d_head, hp.max_len = 8, 32
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_selfcheck_paged_")
    try:
        gen_lm.export_gen_model(tmp, hp, num_slots=2)
        with open(os.path.join(tmp, "gen_meta.json")) as f:
            meta = json.load(f)
        for key in ("page_len", "num_pages", "page_buckets",
                    "page_table_feed"):
            if key not in meta:
                failures.append(f"gen_meta.json missing {key!r}")
        if not failures:
            page_len = int(meta["page_len"])
            pps = -(-int(meta["max_len"]) // page_len)
            pbuckets = [int(p) for p in meta["page_buckets"]]
            if pbuckets != sorted(set(pbuckets)):
                failures.append("page_buckets not strictly increasing: "
                                f"{pbuckets}")
            if pbuckets and pbuckets[-1] != pps:
                failures.append(f"largest page bucket {pbuckets[-1]} != "
                                f"pages/slot {pps} (bucket escape)")
            for label, r in analysis.lint_gen_bundle(tmp):
                for d in r.diagnostics:
                    failures.append(f"[{label}] {d.severity}[{d.code}]: "
                                    f"{d.message}")
            decode = load_saved_program(os.path.join(tmp, "decode"))
            fn = cost.row_cost_fn(decode[0],
                                  batch_var=meta["page_table_feed"],
                                  dim=1, probe_rows=(1, max(pps, 2)))
            if not fn(pps) > fn(1):
                failures.append(
                    "cost model does not price pages: decode flops at "
                    f"{pps} pages ({fn(pps):.0f}) <= at 1 page "
                    f"({fn(1):.0f})")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return _section("paged-kv",
                    "page meta + paged decode lint + page-proportional "
                    "cost", failures)


def _check_embedding():
    """Sharded-embedding gate: a fresh wide_and_deep build's row-
    sharding plan (tables + sparse optimizer moments) verifies clean
    under PTA016/PTA017, the cost model prices the table gather's
    bytes, and the HBM census attributes exactly the tables' bytes to
    the ``embedding`` collection."""
    from paddle_tpu.analysis import cost
    from paddle_tpu.embedding import plan_sharded_tables
    from paddle_tpu.models import build_train_program, compile_zoo_step

    failures = []
    main, _startup, _feeds, _fetches = build_train_program(
        "wide_and_deep")
    plan = plan_sharded_tables(main, mesh_axes={"model": 2},
                               raise_on_error=False)
    if not plan.tables:
        failures.append("no is_distributed lookup tables found in "
                        "wide_and_deep")
    if not plan.states:
        failures.append("no sparse optimizer accumulators joined the "
                        "sharding plan (the moments must live with "
                        "their rows)")
    for d in plan.diagnostics:
        failures.append(f"[plan] {d.severity}[{d.code}]: {d.message}")

    report = cost.estimate(main)
    gather_bytes = sum(row["bytes"] for row in report.per_op
                       if row["op_type"] == "lookup_table")
    if "lookup_table" in report.uncovered or gather_bytes <= 0:
        failures.append("cost model does not price the table gather's "
                        f"bytes (got {gather_bytes})")
    for t in ("lookup_table_grad", "merge_selected_rows",
              "get_tensor_from_selected_rows"):
        if t not in cost.covered_op_types():
            failures.append(f"sparse op {t!r} has no cost rule")

    scope = compile_zoo_step("wide_and_deep", batch=4)
    from paddle_tpu.obs.perf import hbm_census
    census = hbm_census(scope)
    expected = 0
    block = main.global_block()
    for name in plan.tables:
        v = block.var(name)
        expected += 4 * int(v.shape[0]) * int(v.shape[1])
    if census.get("embedding") != expected:
        failures.append(
            f"census attributes {census.get('embedding')} embedding "
            f"bytes; the plan's tables hold {expected}")
    return _section("embedding",
                    "sharded-table plan verification + gather cost + "
                    "census attribution", failures)


# ---------------------------------------------------------------------------
# registry scanners (the doc/code lockstep gates)
# ---------------------------------------------------------------------------

def _check_diagnostic_registry():
    from paddle_tpu.analysis.diagnostics import DIAGNOSTIC_CODES

    emitted = set()
    for path, text in _iter_sources():
        rel = os.path.relpath(path, SRC_ROOT)
        if os.path.dirname(rel) != "analysis" or \
                os.path.basename(rel) == "diagnostics.py":
            continue
        emitted.update(_CODE.findall(text))
    documented = set(_DOC_CODE.findall(_read_doc("static_analysis.md")))
    failures = []
    for code in sorted(emitted - set(DIAGNOSTIC_CODES)):
        failures.append(f"emitted but undeclared: {code}")
    for code in sorted(set(DIAGNOSTIC_CODES) - emitted):
        failures.append(f"declared but no pass emits it: {code}")
    for code in sorted(set(DIAGNOSTIC_CODES) - documented):
        failures.append(f"undocumented in static_analysis.md: {code}")
    for code in sorted(documented - set(DIAGNOSTIC_CODES)):
        failures.append(f"documented but unknown: {code}")
    return _section("diagnostic-registry",
                    f"{len(DIAGNOSTIC_CODES)} codes declared/emitted/"
                    f"documented in lockstep", failures)


def _emitted_metric_names():
    names = set()
    latency = set()
    for path, text in _iter_sources():
        names.update(_METRIC_LITERAL.findall(text))
        found = _METRIC_LATENCY.findall(text)
        latency.update(found)
        names.update(found)
        for suffix in _METRIC_STAGE.findall(text):
            names.add(f"datapipe.<stage>.{suffix}")
        if path.endswith("profiler.py"):
            names.update(_METRIC_MIRROR.findall(text))
    names.update(f"{n}.errors" for n in latency)
    return names


def _check_metric_registry():
    documented = set(_DOC_METRIC.findall(_read_doc("observability.md")))
    failures = []
    for name in sorted(_emitted_metric_names()):
        if name in documented:
            continue
        if name.endswith(".errors") and "<series>.errors" in documented:
            continue
        m = re.match(r"datapipe\.[a-zA-Z0-9_]+\.([a-zA-Z0-9_]+)$", name)
        if m and f"datapipe.<stage>.{m.group(1)}" in documented:
            continue
        failures.append(f"emitted but undocumented: {name}")
    return _section("metric-registry",
                    f"{len(documented)} documented metric rows",
                    failures)


def _check_failpoint_registry():
    fired = set()
    for path, text in _iter_sources():
        if os.path.relpath(path, SRC_ROOT) == os.path.join("fault",
                                                           "chaos.py"):
            continue
        fired.update(_FIRE.findall(text))
    documented = set(_DOC_FAILPOINT.findall(
        _read_doc("fault_tolerance.md")))
    failures = [f"fired but undocumented: {n}"
                for n in sorted(fired - documented)]
    return _section("failpoint-registry",
                    f"{len(fired)} fire sites scanned", failures)


# ---------------------------------------------------------------------------
# observability-plane gates: SLO spec schema + bench trajectory schema
# ---------------------------------------------------------------------------

def _check_slo_spec():
    """The SLO spec schema validator runs against the documented
    example spec (so the validator itself is exercised on every
    selfcheck) AND against the operator's armed ``PADDLE_TPU_SLO`` file
    when set — a malformed spec fails HERE, not as a runtime warning
    three breaches too late."""
    from paddle_tpu.obs import slo

    failures = [f"EXAMPLE_SPEC: {p}"
                for p in slo.validate_spec(slo.EXAMPLE_SPEC)]
    path = os.environ.get(slo.SLO_ENV, "").strip()
    detail = "example spec"
    if path:
        detail += f" + {slo.SLO_ENV}={path}"
        try:
            slo.load_spec(path)
        except (OSError, ValueError) as e:
            failures.extend(str(e).splitlines())
    return _section("slo-spec", detail, failures)


def _check_controller_policy():
    """The autoscaler policy schema validator runs against the
    documented example policy AND against the operator's armed
    ``PADDLE_TPU_AUTOSCALE`` file when set — a malformed policy fails
    HERE, not as a disarmed controller discovered mid-incident."""
    from paddle_tpu.fleet import controller

    failures = [f"EXAMPLE_POLICY: {p}"
                for p in controller.validate_policy(
                    controller.EXAMPLE_POLICY)]
    path = os.environ.get(controller.POLICY_ENV, "").strip()
    detail = "example policy"
    if path:
        detail += f" + {controller.POLICY_ENV}={path}"
        try:
            controller.load_policy(path)
        except (OSError, ValueError) as e:
            failures.extend(str(e).splitlines())
    return _section("controller-policy", detail, failures)


def _check_ckpt_manifest():
    """Checkpoint-manifest schema gate: write a fresh SHARD-format
    checkpoint (synthetic state, no executor, no program) through the
    real ``fault.shard_ckpt`` writer + atomic commit, and prove the
    manifest's topology record is present and self-consistent —
    ``verify_checkpoint`` passes (per-shard hashes AND topology
    cross-checks), and a deliberately tampered topology fails.  The
    elastic-resume contract breaks silently if the schema drifts; this
    fails the static gate instead."""
    import json

    import numpy as np

    from paddle_tpu.fault import shard_ckpt
    from paddle_tpu.fault.checkpoint import (CorruptCheckpoint,
                                             MANIFEST_NAME,
                                             commit_checkpoint,
                                             verify_checkpoint)
    from paddle_tpu.parallel.mesh import make_mesh

    failures = []
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_selfcheck_ckpt_")
    try:
        mesh = make_mesh()
        dp = int(mesh.devices.shape[0])
        state = {"w": np.arange(8 * dp * 3, dtype="float32").reshape(
                     8 * dp, 3),
                 "moment.w": np.ones((8 * dp, 3), "float32"),
                 "lr": np.asarray([0.1], "float32")}
        topo = shard_ckpt.build_topology(
            mesh, state, {"moment.w": ("data", None)})
        tmp_dir = os.path.join(tmp, ".tmp-ckpt-1")
        final = os.path.join(tmp, "ckpt-1")
        os.makedirs(tmp_dir)
        shard_ckpt.write_state(tmp_dir, state, topo, step=1)
        commit_checkpoint(tmp_dir, final, step=1,
                          extra={"topology": topo})
        manifest = shard_ckpt.read_manifest(final)
        if manifest is None or "topology" not in manifest:
            failures.append("committed manifest lacks a topology record")
        else:
            failures.extend(shard_ckpt.validate_topology(manifest))
            try:
                verify_checkpoint(final)
            except CorruptCheckpoint as e:
                failures.append(f"fresh shard checkpoint fails "
                                f"verification: {e}")
            rec = manifest["topology"]["shards"]["moment.w"]
            if dp > 1 and rec["num_shards"] != dp:
                failures.append(
                    f"moment.w should shard {dp}-way over `data`, "
                    f"topology records {rec['num_shards']}")
            # the negative direction: a tampered record must FAIL
            manifest["topology"]["shards"]["moment.w"]["num_shards"] = \
                rec["num_shards"] + 1
            with open(os.path.join(final, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f)
            try:
                verify_checkpoint(final)
                failures.append("tampered topology record passed "
                                "verification")
            except CorruptCheckpoint:
                pass
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return _section("ckpt-manifest",
                    "shard-checkpoint topology record write/verify "
                    "round-trip", failures)


def _check_perf():
    """Device-performance gate: a fresh compile of a zoo model must
    yield a well-formed cost/memory record (flops, bytes, memory
    breakdown, phase times all present), and the ``profile compile
    --json`` schema must validate — so the MFU gauge, the profile CLI,
    and the bench trajectory's measured_mfu row can't silently lose
    their data source to a jax API drift."""
    from paddle_tpu.models import compile_zoo_step
    from paddle_tpu.obs import perf

    failures = []
    before = {r["key"] for r in perf.records()}
    scope = compile_zoo_step("mnist")
    fresh = [r for r in perf.records() if r["key"] not in before]
    with_cost = [r for r in fresh if r["flops"]]
    if not with_cost:
        failures.append("fresh zoo compile captured no cost record "
                        "(capture disabled or cost_analysis "
                        "unavailable?)")
    for r in with_cost:
        if r["memory"] is None:
            failures.append(f"{r['key']}: no memory_analysis breakdown")
        if any(r["phases"].get(k) is None for k in perf.PHASE_KEYS):
            failures.append(f"{r['key']}: incomplete compile phases")
    if with_cost and not any(r["mfu"] for r in with_cost):
        failures.append("no record derived a live MFU after the step")
    failures.extend(perf.validate_report(perf.compile_report()))
    census = perf.hbm_census(scope)
    if not census.get("params") or not census.get("optimizer"):
        failures.append(
            f"hbm census failed to attribute params/optimizer state: "
            f"{ {k: census.get(k) for k in ('params', 'optimizer')} }")
    return _section("perf",
                    "fresh zoo compile -> cost/memory record, "
                    "profile-compile schema, hbm census attribution",
                    failures)


def _check_opt():
    """Optimization-pipeline gate: the full pipeline runs over every
    zoo model (main AND startup), no pass is sandwich-aborted, every
    OPTIMIZED program still lints clean (the passes must not trade
    correctness findings for speed), the static cost report keeps its
    schema, and a one-step executor equivalence spot-check proves the
    optimized program computes the same fetches."""
    import numpy as np

    from paddle_tpu import analysis
    from paddle_tpu.analysis import cost
    from paddle_tpu.analysis.opt import optimize_program
    from paddle_tpu.models import ZOO_MODELS, build_train_program

    failures = []
    for name in ZOO_MODELS:
        main, startup, feeds, fetches = build_train_program(name)
        for label, prog, fd, ft in ((name, main, feeds, fetches),
                                    (f"{name}/startup", startup, None,
                                     None)):
            optimized, report = optimize_program(prog, feed_names=fd,
                                                 fetch_names=ft)
            for p in report.aborted_passes:
                failures.append(f"[{label}] pass {p!r} was "
                                f"sandwich-aborted")
            r = analysis.lint_program(optimized, feed_names=fd,
                                      fetch_names=ft)
            for d in r.diagnostics:
                failures.append(f"[{label}] optimized program: "
                                f"{d.severity}[{d.code}]: {d.message}")
        failures.extend(
            f"[{name}] cost report: {p}"
            for p in cost.validate_cost_report(
                cost.estimate(main).to_dict()))

    # equivalence spot-check (one cheap model; the zoo-wide harness is
    # tests/test_opt_equivalence.py): same startup init, one step,
    # fetches must agree
    import paddle_tpu as fluid
    main, startup, feeds, fetches = build_train_program("mnist")
    main.random_seed = startup.random_seed = 3
    optimized, _ = optimize_program(main, feed_names=feeds,
                                    fetch_names=fetches)
    from paddle_tpu.models import synth_feed
    outs = []
    for prog in (main, optimized):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            outs.append(exe.run(prog,
                                feed=synth_feed(main, feeds),
                                fetch_list=fetches, scope=scope))
    for ft, a, b in zip(fetches, outs[0], outs[1]):
        if not np.allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                           atol=1e-6):
            failures.append(f"equivalence spot-check: fetch {ft!r} "
                            f"diverged under optimization")
    return _section("opt",
                    "zoo-wide pipeline run, optimized-program lint, "
                    "cost schema, equivalence spot-check", failures)


def _check_ledger():
    """Run-ledger gate: a fresh ledger round-trips rows through its
    schema validators and atomic segment rotation, the resume cursor
    rewinds exactly, the documented example drift spec validates (and a
    broken one fails), and a malformed row is refused — the persistence
    layer every divergence hunt reads must not drift silently."""
    from paddle_tpu.obs import ledger

    failures = []
    failures.extend(f"EXAMPLE_DRIFT_SPEC: {p}"
                    for p in ledger.validate_spec(
                        ledger.EXAMPLE_DRIFT_SPEC))
    if not ledger.validate_spec({"version": 1, "rules": []}):
        failures.append("validate_spec accepted an empty rules list")
    if not ledger.validate_row({"step": -1, "time_unix": 0.0}):
        failures.append("validate_row accepted a negative step")
    if not ledger.validate_row({"step": 0, "time_unix": 1.0,
                                "bogus": 2}):
        failures.append("validate_row accepted an unknown field")
    tmp = tempfile.mkdtemp(prefix="paddle_tpu_selfcheck_ledger_")
    try:
        led = ledger.RunLedger(os.path.join(tmp, "run"), rotate_rows=4,
                               flush_every=1, install=False)
        for _ in range(10):
            led.note_step(fetch_names=("loss",), fetches=([0.5],))
        cursor = led.state_dict()
        for _ in range(3):
            led.note_step(fetch_names=("loss",), fetches=([0.5],))
        led.load_state_dict(cursor)
        led.close()
        rows = ledger.read_rows(os.path.join(tmp, "run"))
        if len(rows) != 10:
            failures.append(f"rotation/rewind round-trip kept "
                            f"{len(rows)} rows, want 10")
        if [r["step"] for r in rows] != list(range(10)):
            failures.append("rewound ledger lost step monotonicity: "
                            f"{[r['step'] for r in rows]}")
        segs = [n for n in os.listdir(os.path.join(tmp, "run"))
                if n.startswith("seg-")]
        if len(segs) < 2:
            failures.append(f"rotate_rows=4 over 10 rows produced "
                            f"{len(segs)} segment(s), want >= 2")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return _section("ledger",
                    "row/spec schema validators, rotation + resume-"
                    "cursor round-trip", failures)


def _check_sessions():
    """Resumable-session gate: the resume-event schema round-trips every
    documented wire shape (token events, clean/legacy/new terminal
    tails, migrate hand-backs) through JSON and the validators, a
    malformed event fails, drain checkpoints validate, and the bounded
    session table keeps its eviction invariants (capacity ceiling,
    orphan accounting, eviction-on-done) — protocol drift fails a
    release gate, not a production failover."""
    import json as _json

    from paddle_tpu.fleet import sessions

    failures = []
    good_events = [
        {"token": 7, "index": 0},
        {"token": 3, "index": 41},
        {"done": True, "finish_reason": "eos", "tokens": 5,
         "token_index": 5},
        # legacy error tail: no token_index / retryable — must parse
        {"error": {"type": "upstream_died", "message": "x"},
         "done": True},
        # new error tail: token_index high-water mark + retryable flag
        {"error": {"type": "batcher_crashed", "message": "x"},
         "done": True, "token_index": 9, "retryable": True},
        {"migrate": {"resume_from": 4, "remaining_tokens": 12},
         "done": True, "token_index": 4, "retryable": True},
    ]
    for ev in good_events:
        round_tripped = _json.loads(_json.dumps(ev))
        problems = sessions.validate_stream_event(round_tripped)
        if problems:
            failures.append(f"valid event {ev} rejected: {problems}")
    bad_events = [
        {"token": 7},                                   # no index
        {"token": 7, "index": -1},
        {"token": 7, "index": 0, "done": True},         # token+terminal
        {"done": True},                                 # no kind
        {"done": True, "finish_reason": "eos",
         "error": {"type": "x"}},                       # two kinds
        {"migrate": {"resume_from": 4}, "done": True},  # not retryable
        {"error": "boom", "done": True},                # error not dict
    ]
    for ev in bad_events:
        if not sessions.validate_stream_event(ev):
            failures.append(f"invalid event {ev} accepted")
    ckpt = {"prompt": [1, 2, 3], "tokens": [4, 5],
            "remaining_tokens": 7, "eos_id": None, "reason": "draining"}
    problems = sessions.validate_checkpoint(
        _json.loads(_json.dumps(ckpt)))
    if problems:
        failures.append(f"valid checkpoint rejected: {problems}")
    if not sessions.validate_checkpoint({"prompt": [],
                                         "tokens": [],
                                         "remaining_tokens": -1,
                                         "reason": ""}):
        failures.append("invalid checkpoint accepted")
    # table invariants: bounded, LRU eviction counts unfinished
    # sessions as orphaned, finish() evicts
    table = sessions.SessionTable(capacity=4)
    for i in range(7):
        table.begin(f"s{i}", "127.0.0.1:1", [1, 2], 8)
    if len(table) > 4:
        failures.append(f"capacity 4 table holds {len(table)}")
    if table.orphaned != 3:
        failures.append(f"7 begins over capacity 4 orphaned "
                        f"{table.orphaned}, want 3")
    if table.owner("s6") != "127.0.0.1:1":
        failures.append("youngest session evicted before the LRU one")
    table.finish("s6")
    if table.owner("s6") is not None or len(table) != 3:
        failures.append("finish() did not evict the session")
    if table.finish("s6") is not None:
        failures.append("finish() of an unknown session returned "
                        "an entry")
    snap = table.snapshot()
    if snap["count"] != 3 or snap["orphaned"] != 3 or \
            len(snap["sessions"]) != 3:
        failures.append(f"snapshot out of step with the table: {snap}")
    return _section("sessions",
                    "resume-event/checkpoint schema round-trip, "
                    "session-table eviction invariants", failures)


def _check_bench_trajectory():
    """``bench check --dry`` against the repo's BENCH_TRAJECTORY.json:
    a drifted or malformed trajectory schema fails the static gate (the
    regression COMPARISON stays in `paddle_tpu bench check` proper —
    perf verdicts don't belong in a schema gate)."""
    from paddle_tpu.obs import bench_history

    path = bench_history.default_path()
    report = bench_history.check(path=path, dry=True)
    failures = list(report["problems"])
    detail = f"schema of {os.path.basename(path)}"
    return _section("bench-trajectory", detail, failures)


def run_selfcheck():
    """Run every section; returns the report dict."""
    sections = [
        _check_zoo_lint(),
        _check_zoo_distribute(),
        _check_zoo_pipeline(),
        _check_gen_bundle(),
        _check_paged_kv(),
        _check_embedding(),
        _check_diagnostic_registry(),
        _check_metric_registry(),
        _check_failpoint_registry(),
        _check_slo_spec(),
        _check_controller_policy(),
        _check_opt(),
        _check_ledger(),
        _check_sessions(),
        _check_bench_trajectory(),
        _check_ckpt_manifest(),
        _check_perf(),
    ]
    return {"ok": all(s["ok"] for s in sections), "sections": sections}
