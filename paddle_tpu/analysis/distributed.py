"""Distributed-program verifier: cross-program checks over the FAMILIES
a transpile produces.

The single-program passes (structural/typecheck/lints) prove one program
well-formed; this module proves a *set* of programs consistent with each
other — the class of fault that otherwise surfaces as a hang or a
cryptic trace error deep in the multichip runtime:

* **collective matching** (PTA011/PTA012) — every member of an SPMD
  family (replicas, pipeline stages run as ``lax.switch`` branches)
  must emit the SAME collective sequence: same ops, same program order,
  same axis/root/participants/shape/dtype.  A member whose collectives
  are reordered relative to its peers is a *static deadlock* — device A
  enters an all-reduce while device B waits in a broadcast, forever.
* **Send/Recv pairing** (PTA013) — in a trainer/pserver-style
  transpiled pair, every ``send`` must have exactly one matching
  ``recv`` of the same variable in a peer program, with agreeing
  declared shape/dtype.  An unpaired end blocks forever at runtime.
* **split reassembly** (PTA014) — pserver-side parameter/gradient
  blocks (``<name>.block<k>``, the reference ``distributed_splitter``
  convention) must sum back to the original variable's shape.
* **stage boundary agreement** (PTA015) — pipeline boundary carriers
  must agree between producer and consumer stages: same names in the
  same order (the carrier layout is positional), same shape/dtype, and
  every value a stage consumes from upstream must actually ride the
  boundary before it (generalizes the i32 carrier-lane check).
* **sharding propagation** (PTA016/PTA017) — PartitionSpec-style
  placements are validated against the mesh and propagated from
  feed/persistable roots through per-op :func:`sharding_rule` functions
  (the ``typecheck.rule`` idiom); a provably invalid spec (unknown
  axis, rank overflow, indivisible dim, Param/Grad disagreement) is an
  error, an implicit full reshard (operands provably sharded
  differently) a warning.  This is the foundation the sharded-embedding
  work (ROADMAP item 3) builds on.
* **recompile hazards** (PTA018/PTA019) — a gen bundle's prompt
  buckets must be strictly increasing and inside the cache geometry
  (else a declared feed escapes its warmed ``lod.row_bucket`` edges and
  compiles per request), and the prefill/decode pair must agree on the
  constant-jit-key contract: fully static decode feeds, cache tensors
  matching ``gen_meta.json``'s geometry, prefill K/V fetches matching
  the decode cache signature.

Like every analysis pass, the contract is ZERO false positives: checks
fire only on facts provable from the IR (and the declared metadata)
alone; unknown shapes/dtypes/specs stay silent.
"""

from __future__ import annotations

import json
import os
import re

from paddle_tpu.analysis.diagnostics import (Diagnostic,
                                             ProgramVerificationError)

__all__ = [
    "COLLECTIVE_OP_TYPES", "collective_signature",
    "check_collective_match", "check_send_recv", "check_param_splits",
    "check_transpiled_pair", "check_stage_set", "check_pipeline_stages",
    "sharding_rule", "sharding_rules", "check_sharding",
    "check_distributed_spec", "check_gen_bundle", "lint_gen_bundle",
    "lint_pipeline", "lint_pair", "verify_gen_bundle",
    "load_saved_program",
]

#: collective op family (parallel/collective.py) — blocking rendezvous
#: points every participant must reach in the same order
COLLECTIVE_OP_TYPES = frozenset({
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_broadcast", "c_allgather", "c_reducescatter",
    "c_alltoall",
})

_SPLIT_BLOCK = re.compile(r"^(?P<base>.+)\.block(?P<idx>\d+)$")


def _sub_blocks(op):
    for a in op.attrs.values():
        if a.__class__.__name__ == "Block":
            yield a


def _var_meta(block, name):
    """Declared (shape, dtype) of ``name`` or (None, None)."""
    try:
        v = block.var(name)
    except KeyError:
        return None, None
    shape = None if v.shape is None else tuple(int(d) for d in v.shape)
    return shape, v.dtype


# ---------------------------------------------------------------------------
# collective matching (PTA011 / PTA012)
# ---------------------------------------------------------------------------

def collective_signature(ops, block):
    """Program-order collective trace of an op list: one entry per
    collective op (sub-blocks recursed in order), carrying everything
    peers must agree on."""
    sig = []

    def walk(op_list):
        for i, op in enumerate(op_list):
            if op.type in COLLECTIVE_OP_TYPES:
                x = op.input("X")
                shape, dtype = _var_meta(block, x[0]) if x else (None, None)
                sig.append({
                    "type": op.type,
                    "axis": op.attr("axis"),
                    "root": op.attr("root"),
                    "nranks": op.attr("nranks"),
                    "var": x[0] if x else None,
                    "shape": shape, "dtype": dtype,
                    "op_index": i, "op": op,
                })
            for sub in _sub_blocks(op):
                walk(sub.ops)

    walk(list(ops))
    return sig


def program_collective_signature(program):
    block = program.global_block()
    return collective_signature(block.ops, block)


def _attrs_agree(a, b):
    """Both declared and different -> disagree; unknown matches all."""
    return a is None or b is None or a == b


def check_collective_match(members):
    """``members``: list of ``(label, ops, block)`` (or
    ``(label, program)``) — the SPMD family.  Returns diagnostics.

    Sequence-level divergence (count or op kind at a position) is
    PTA011 — a static deadlock: the members rendezvous in different
    orders.  A matched position whose axis/root/participants/shape/
    dtype provably differ is PTA012 — the rendezvous happens, on
    inconsistent data."""
    diags = []
    sigs = []
    for m in members:
        if len(m) == 2:
            label, program = m
            sigs.append((label, program_collective_signature(program)))
        else:
            label, ops, block = m
            sigs.append((label, collective_signature(ops, block)))
    if len(sigs) < 2:
        return diags
    ref_label, ref = sigs[0]
    for label, sig in sigs[1:]:
        n = min(len(ref), len(sig))
        divergence = None
        for i in range(n):
            if ref[i]["type"] != sig[i]["type"]:
                divergence = i
                break
        if divergence is not None:
            a, b = ref[divergence], sig[divergence]
            diags.append(Diagnostic(
                "PTA011",
                f"collective #{divergence} diverges between "
                f"`{ref_label}` and `{label}`: `{a['type']}` (on "
                f"`{a['var']}`) vs `{b['type']}` (on `{b['var']}`) — "
                f"the members rendezvous in different orders and "
                f"deadlock on device",
                op_index=b["op_index"], op_type=b["type"], var=b["var"],
                site=getattr(b["op"], "creation_site", None),
                program=label))
            continue
        if len(ref) != len(sig):
            longer_label = ref_label if len(ref) > len(sig) else label
            extra = (ref if len(ref) > len(sig) else sig)[n]
            diags.append(Diagnostic(
                "PTA011",
                f"`{ref_label}` emits {len(ref)} collective(s) but "
                f"`{label}` emits {len(sig)} — `{longer_label}`'s "
                f"`{extra['type']}` (on `{extra['var']}`) has no "
                f"rendezvous partner and blocks forever",
                op_index=extra["op_index"], op_type=extra["type"],
                var=extra["var"],
                site=getattr(extra["op"], "creation_site", None),
                program=longer_label))
            continue
        for i in range(n):
            a, b = ref[i], sig[i]
            bad = []
            if not _attrs_agree(a["axis"], b["axis"]):
                bad.append(f"axis {a['axis']!r} vs {b['axis']!r}")
            if not _attrs_agree(a["root"], b["root"]):
                bad.append(f"root {a['root']!r} vs {b['root']!r}")
            if not _attrs_agree(a["nranks"], b["nranks"]):
                bad.append(f"participants {a['nranks']!r} vs "
                           f"{b['nranks']!r}")
            if a["shape"] is not None and b["shape"] is not None and \
                    a["shape"] != b["shape"]:
                bad.append(f"shape {a['shape']} vs {b['shape']}")
            if not _attrs_agree(a["dtype"], b["dtype"]):
                bad.append(f"dtype {a['dtype']} vs {b['dtype']}")
            if bad:
                diags.append(Diagnostic(
                    "PTA012",
                    f"collective #{i} `{b['type']}` matches between "
                    f"`{ref_label}` and `{label}` but the members "
                    f"disagree on " + "; ".join(bad),
                    op_index=b["op_index"], op_type=b["type"],
                    var=b["var"],
                    site=getattr(b["op"], "creation_site", None),
                    program=label))
    return diags


# ---------------------------------------------------------------------------
# Send/Recv pairing (PTA013) + split reassembly (PTA014)
# ---------------------------------------------------------------------------

def _send_recv_sites(program):
    sends, recvs = [], []
    block = program.global_block()
    for i, op in enumerate(block.ops):
        if op.type == "send":
            for n in op.input("X"):
                sends.append((n, i, op))
        elif op.type == "recv":
            for n in op.output("Out"):
                recvs.append((n, i, op))
    return sends, recvs


def check_send_recv(members):
    """``members``: list of ``(label, program)`` — typically the
    trainer and its pserver program(s).  Every ``send`` of a variable
    must have a matching ``recv`` of the same name in a PEER program
    (and vice versa), with agreeing declared shape/dtype."""
    diags = []
    per = []
    for label, program in members:
        sends, recvs = _send_recv_sites(program)
        per.append((label, program, sends, recvs))
    for label, program, sends, recvs in per:
        peers_recv = {}
        peers_send = {}
        for plabel, pprog, psends, precvs in per:
            if plabel == label:
                continue
            for n, i, op in precvs:
                peers_recv.setdefault(n, []).append((plabel, pprog, i, op))
            for n, i, op in psends:
                peers_send.setdefault(n, []).append((plabel, pprog, i, op))
        block = program.global_block()
        for n, i, op in sends:
            matches = peers_recv.get(n, [])
            if not matches:
                diags.append(Diagnostic(
                    "PTA013",
                    f"`{label}` sends `{n}` (op #{i}) but no peer "
                    f"program receives it — the send blocks forever",
                    op_index=i, op_type="send", var=n,
                    site=getattr(op, "creation_site", None),
                    program=label))
                continue
            s_shape, s_dtype = _var_meta(block, n)
            for plabel, pprog, pi, pop in matches:
                r_shape, r_dtype = _var_meta(pprog.global_block(), n)
                bad = []
                if s_shape is not None and r_shape is not None and \
                        s_shape != r_shape:
                    bad.append(f"shape {s_shape} vs {r_shape}")
                if s_dtype is not None and r_dtype is not None and \
                        s_dtype != r_dtype:
                    bad.append(f"dtype {s_dtype} vs {r_dtype}")
                if bad:
                    diags.append(Diagnostic(
                        "PTA013",
                        f"`{label}` sends `{n}` but `{plabel}` "
                        f"receives it with disagreeing "
                        + "; ".join(bad),
                        op_index=pi, op_type="recv", var=n,
                        site=getattr(pop, "creation_site", None),
                        program=plabel))
        for n, i, op in recvs:
            if n not in peers_send:
                diags.append(Diagnostic(
                    "PTA013",
                    f"`{label}` receives `{n}` (op #{i}) but no peer "
                    f"program sends it — the recv blocks forever",
                    op_index=i, op_type="recv", var=n,
                    site=getattr(op, "creation_site", None),
                    program=label))
    return diags


def check_param_splits(trainer, pservers):
    """``trainer``: ``(label, program)``; ``pservers``: list of the
    same.  Pserver-side split blocks (``<name>.block<k>``) of a trainer
    variable must reassemble EXACTLY: contiguous block indices, equal
    tail dims, leading dims summing to the original (PTA014)."""
    diags = []
    t_label, t_prog = trainer
    t_block = t_prog.global_block()
    blocks = {}  # base name -> {idx: (shape, label)}
    for label, pprog in pservers:
        for blk in pprog.blocks:
            for v in blk.vars.values():
                m = _SPLIT_BLOCK.match(v.name)
                if not m:
                    continue
                base = m.group("base")
                if not t_block.has_var(base):
                    continue
                shape = None if v.shape is None else \
                    tuple(int(d) for d in v.shape)
                blocks.setdefault(base, {})[int(m.group("idx"))] = \
                    (shape, label)
    for base, parts in sorted(blocks.items()):
        orig_shape, _ = _var_meta(t_block, base)
        if orig_shape is None or any(d < 0 for d in orig_shape):
            continue
        idxs = sorted(parts)
        if idxs != list(range(len(idxs))):
            missing = sorted(set(range(idxs[-1] + 1)) - set(idxs))
            diags.append(Diagnostic(
                "PTA014",
                f"split of `{base}` {orig_shape} is missing block "
                f"index(es) {missing}: pserver programs hold blocks "
                f"{idxs}", var=base, program=t_label))
            continue
        shapes = [parts[i][0] for i in idxs]
        if any(s is None or any(d < 0 for d in s) for s in shapes):
            continue  # unknown block shapes: nothing provable
        tails = {tuple(s[1:]) for s in shapes}
        if len(tails) > 1 or (tails and
                              next(iter(tails)) != tuple(orig_shape[1:])):
            diags.append(Diagnostic(
                "PTA014",
                f"split blocks of `{base}` {orig_shape} disagree on "
                f"tail dims: {sorted(tails)} (original tail "
                f"{tuple(orig_shape[1:])})", var=base, program=t_label))
            continue
        total = sum(s[0] for s in shapes)
        if total != orig_shape[0]:
            diags.append(Diagnostic(
                "PTA014",
                f"split blocks of `{base}` sum to {total} rows but the "
                f"original is {orig_shape} — the splits do not "
                f"reassemble to the parameter",
                var=base, program=t_label))
    return diags


def check_transpiled_pair(trainer, pservers):
    """The whole trainer/pserver-pair contract: collective matching
    across the family, Send/Recv pairing, split reassembly."""
    members = [trainer] + list(pservers)
    diags = []
    diags.extend(check_send_recv(members))
    diags.extend(check_param_splits(trainer, pservers))
    return diags


# ---------------------------------------------------------------------------
# pipeline stage set (PTA011 across stages, PTA015 boundaries)
# ---------------------------------------------------------------------------

def check_stage_set(block, stage_ops, boundaries, feed_names=(),
                    param_names=None):
    """Validate a ``split_program`` stage set against its boundary
    carriers (the generalization of the i32 carrier-lane check):

    * every non-parameter value a stage consumes from upstream must
      ride the boundary immediately before it (PTA015 — it would
      simply be absent from the flat carrier at runtime);
    * every boundary name must be produced by an earlier stage or be a
      feed (PTA015 — the carrier would pack an undefined value);
    * the stages, run as ``lax.switch`` branches, must emit matching
      collective sequences (PTA011/PTA012 — a branch-local collective
      its peers don't run deadlocks the mesh).
    """
    from paddle_tpu.framework import Parameter

    def is_param(name):
        v = block.vars.get(name)
        return v is not None and (isinstance(v, Parameter) or
                                  getattr(v, "persistable", False))

    if param_names is None:
        param_names = {n for n in block.vars if is_param(n)}
    feed_set = set(feed_names)
    diags = []

    produced_by = {}
    for s, sops in enumerate(stage_ops):
        for op in sops:
            for n in op.output_arg_names:
                if n:
                    produced_by.setdefault(n, s)

    def external_inputs(op):
        names = [n for n in op.input_arg_names if n]
        for sub in _sub_blocks(op):
            for sop in sub.ops:
                names.extend(external_inputs(sop))
        return names

    for s, sops in enumerate(stage_ops):
        if s == 0:
            continue
        carried = set(boundaries[s]) if s < len(boundaries) else set()
        for op in sops:
            for n in external_inputs(op):
                if n in param_names or n in carried:
                    continue
                src = produced_by.get(n)
                if src is not None and src >= s:
                    continue  # produced locally or downstream-fed
                if src is None and n not in feed_set:
                    continue  # scope state, not a carrier concern
                diags.append(Diagnostic(
                    "PTA015",
                    f"stage {s} op `{op.type}` consumes `{n}` "
                    f"(produced by "
                    f"{'the feed' if src is None else f'stage {src}'}) "
                    f"but the boundary before stage {s} does not carry "
                    f"it — the value is absent from the flat carrier "
                    f"at runtime",
                    op_type=op.type, var=n,
                    site=getattr(op, "creation_site", None),
                    program=f"stage{s}"))
                break  # one finding per op keeps the report readable
    for b, names in enumerate(boundaries):
        for n in names:
            src = produced_by.get(n)
            if src is None and n not in feed_set:
                if block.has_var(n):  # scope state rides nothing
                    continue
                diags.append(Diagnostic(
                    "PTA015",
                    f"boundary {b} carries `{n}`, which no stage "
                    f"produces and no feed provides — the carrier "
                    f"would pack an undefined value", var=n,
                    program=f"boundary{b}"))
            elif src is not None and b <= src < len(stage_ops) and \
                    b != len(boundaries) - 1 and b > 0:
                diags.append(Diagnostic(
                    "PTA015",
                    f"boundary {b} carries `{n}` but it is only "
                    f"produced later, by stage {src} — the carrier "
                    f"would pack an undefined value", var=n,
                    program=f"boundary{b}"))

    members = [(f"stage{s}", sops, block)
               for s, sops in enumerate(stage_ops)]
    diags.extend(check_collective_match(members))
    return diags


def check_pipeline_stages(stages):
    """``stages``: ordered list of ``(label, program, in_names,
    out_names)`` — per-stage programs of one pipeline (the
    multi-program CLI unit).  Adjacent stages must agree on the
    carrier: the producer's out list IS the consumer's in list (the
    flat carrier layout is positional, so order matters), and
    same-named vars must declare agreeing shape/dtype (PTA015).
    Collectives must match across all stages (PTA011/PTA012)."""
    diags = []
    for (a_label, a_prog, _a_in, a_out), \
            (b_label, b_prog, b_in, _b_out) in zip(stages, stages[1:]):
        if list(a_out) != list(b_in):
            diags.append(Diagnostic(
                "PTA015",
                f"boundary between `{a_label}` and `{b_label}` "
                f"disagrees: producer emits {list(a_out)} but consumer "
                f"expects {list(b_in)} — the positional carrier layout "
                f"desyncs",
                var=next((n for n, m in zip(a_out, list(b_in) + [None])
                          if n != m), None),
                program=b_label))
            continue
        a_block = a_prog.global_block()
        b_block = b_prog.global_block()
        for n in a_out:
            a_shape, a_dtype = _var_meta(a_block, n)
            b_shape, b_dtype = _var_meta(b_block, n)
            bad = []
            if a_shape is not None and b_shape is not None and \
                    a_shape != b_shape:
                bad.append(f"shape {a_shape} vs {b_shape}")
            if a_dtype is not None and b_dtype is not None and \
                    a_dtype != b_dtype:
                bad.append(f"dtype {a_dtype} vs {b_dtype}")
            if bad:
                diags.append(Diagnostic(
                    "PTA015",
                    f"carrier `{n}` drifts between `{a_label}` "
                    f"(producer) and `{b_label}` (consumer): "
                    + "; ".join(bad), var=n, program=b_label))
    diags.extend(check_collective_match(
        [(label, prog) for label, prog, _i, _o in stages]))
    return diags


# ---------------------------------------------------------------------------
# sharding-spec propagation (PTA016 / PTA017)
# ---------------------------------------------------------------------------

_SHARDING_RULES = {}


def sharding_rule(*op_types):
    """Decorator registering ``fn(op, senv)`` as the sharding
    propagation rule for one or more op types — the distributed analog
    of ``typecheck.rule`` (same registry idiom, same degrade-on-error
    contract)."""

    def deco(fn):
        for t in op_types:
            _SHARDING_RULES[t] = fn
        return fn

    return deco


def sharding_rules():
    return set(_SHARDING_RULES)


def _norm_spec(spec):
    """PartitionSpec / tuple / list -> tuple of axis-or-None (None =
    replicated on that dim); None stays None (unknown placement)."""
    if spec is None:
        return None
    return tuple(spec)


class ShardEnv:
    """name -> placement environment threaded through one program.

    A placement is a tuple of mesh-axis names (or None) per tensor dim;
    ``None`` means *unknown* and matches anything; ``()`` means
    *replicated* (known)."""

    def __init__(self, block, diags, mesh_axes=None):
        self.block = block
        self.diags = diags
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        self.op_index = None
        self._env = {}

    def spec(self, name):
        if not name:
            return None
        return self._env.get(name)

    def input_spec(self, op, slot):
        names = op.input(slot)
        return self.spec(names[0]) if names else None

    def set(self, name, spec):
        if name:
            self._env[name] = _norm_spec(spec)

    def set_output(self, op, slot, spec):
        for n in op.output(slot):
            self.set(n, spec)

    def report(self, code, message, op=None, var=None):
        self.diags.append(Diagnostic(
            code, message, block_idx=self.block.idx,
            op_index=self.op_index,
            op_type=op.type if op is not None else None, var=var,
            site=getattr(op, "creation_site", None)))

    def merge(self, op, slot_a, slot_b, out_slot="Out"):
        """Elementwise-style merge.  Both operands provably sharded,
        and differently, means GSPMD inserts a full reshard to align
        them (PTA017).  One-sided knowledge propagates nothing (the
        unknown operand could carry any placement — silence, not a
        guess)."""
        a = self.input_spec(op, slot_a)
        b = self.input_spec(op, slot_b)
        if a is not None and b is not None and a != b and \
                any(x is not None for x in a) and \
                any(x is not None for x in b):
            an = op.input(slot_a)[0] if op.input(slot_a) else "?"
            bn = op.input(slot_b)[0] if op.input(slot_b) else "?"
            self.report(
                "PTA017",
                f"{op.type} combines `{an}` (sharded {a}) with `{bn}` "
                f"(sharded {b}) — GSPMD will insert an implicit full "
                f"reshard; align the placements or reshard explicitly",
                op=op, var=an)
            self.set_output(op, out_slot, None)
            return
        self.set_output(op, out_slot, a if a == b else None)


def _validate_spec(name, spec, shape, mesh_axes, diags, program=None):
    """Provable ill-formedness of one declared placement (PTA016)."""
    spec = _norm_spec(spec)
    if spec is None:
        return
    if shape is not None and len(spec) > len(shape):
        diags.append(Diagnostic(
            "PTA016",
            f"sharding spec {spec} of `{name}` names "
            f"{len(spec)} dims but the variable has rank "
            f"{len(shape)} ({shape})", var=name, program=program))
        return
    seen_axes = set()
    for d, axis in enumerate(spec):
        if axis is None:
            continue
        if axis in seen_axes:
            diags.append(Diagnostic(
                "PTA016",
                f"sharding spec {spec} of `{name}` uses mesh axis "
                f"`{axis}` on more than one dim", var=name,
                program=program))
            continue
        seen_axes.add(axis)
        if mesh_axes is not None and axis not in mesh_axes:
            diags.append(Diagnostic(
                "PTA016",
                f"sharding spec of `{name}` places dim {d} on mesh "
                f"axis `{axis}`, which the mesh does not have "
                f"(axes: {sorted(mesh_axes)})", var=name,
                program=program))
            continue
        if mesh_axes is not None and shape is not None and \
                d < len(shape) and shape[d] > 0 and \
                shape[d] % int(mesh_axes[axis]) != 0:
            diags.append(Diagnostic(
                "PTA016",
                f"`{name}` dim {d} of size {shape[d]} is not "
                f"divisible by mesh axis `{axis}` of size "
                f"{mesh_axes[axis]} — the shards would be ragged",
                var=name, program=program))


def check_sharding(program, placements, mesh_axes=None, program_label=None):
    """Validate declared ``placements`` (name -> PartitionSpec-like)
    against the program and optionally a mesh-axes size dict, then
    propagate them through the registered :func:`sharding_rule`
    functions.  Returns diagnostics (PTA016 errors, PTA017 warnings)."""
    diags = []
    block = program.global_block()
    for name, spec in sorted(placements.items()):
        shape, _ = _var_meta(block, name)
        if not block.has_var(name):
            diags.append(Diagnostic(
                "PTA016",
                f"sharding spec declared for `{name}`, which is not a "
                f"variable of the program", var=name,
                program=program_label))
            continue
        _validate_spec(name, spec, shape, mesh_axes, diags,
                       program=program_label)
    if any(d.code == "PTA016" for d in diags):
        return diags  # propagation over an invalid plan only cascades

    senv = ShardEnv(block, diags, mesh_axes=mesh_axes)
    for name, spec in placements.items():
        senv.set(name, spec)
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        senv.op_index = i
        fn = _SHARDING_RULES.get(op.type)
        if fn is None:
            for n in op.output_arg_names:
                if n and n not in placements:
                    senv.set(n, None)
            continue
        try:
            fn(op, senv)
        except Exception:
            for n in op.output_arg_names:
                senv.set(n, None)
    if program_label:
        for d in diags:
            if d.program is None:
                d.program = program_label
    return diags


def check_distributed_spec(program, spec, mesh_axes=None,
                           program_label=None):
    """Validate a :class:`DistributeTranspiler` plan: every declared
    param/grad placement well-formed against the program (+ mesh when
    given), param and grad placements agreeing, then the sharding
    propagation pass over the plan."""
    diags = []
    for name in sorted(set(spec.param_specs) & set(spec.grad_specs)):
        p = _norm_spec(spec.param_specs[name])
        g = _norm_spec(spec.grad_specs[name])
        if p is not None and g is not None and p != g:
            diags.append(Diagnostic(
                "PTA016",
                f"`{name}` is placed {p} as a parameter but its "
                f"gradient is placed {g} — the optimizer update would "
                f"combine differently-sharded tensors", var=name,
                program=program_label))
    diags.extend(check_sharding(program, dict(spec.param_specs),
                                mesh_axes=mesh_axes,
                                program_label=program_label))
    return diags


# -- core sharding rules ----------------------------------------------------

_ELEMENTWISE = ("elementwise_add", "elementwise_sub", "elementwise_mul",
                "elementwise_div", "elementwise_max", "elementwise_min",
                "elementwise_pow")


@sharding_rule(*_ELEMENTWISE)
def _s_elementwise(op, senv):
    senv.merge(op, "X", "Y")


@sharding_rule("relu", "sigmoid", "tanh", "exp", "log", "sqrt", "abs",
               "square", "softmax", "gelu", "scale", "assign", "dropout",
               "cast", "clip", "layer_norm", "batch_norm")
def _s_unary(op, senv):
    x = senv.input_spec(op, "X")
    for slot in ("Out", "Y"):
        if op.output(slot):
            senv.set_output(op, slot, x)


@sharding_rule("mul", "matmul")
def _s_matmul(op, senv):
    x = senv.input_spec(op, "X")
    y = senv.input_spec(op, "Y")
    # contraction sharded on ONE side only is the classic implicit
    # all-gather; sharded on both it lowers to a clean psum
    if x is not None and y is not None and len(x) >= 1 and len(y) >= 1:
        kx = x[-1]
        ky = y[-2] if len(y) >= 2 else y[0]
        if (kx or ky) and kx != ky:
            senv.report(
                "PTA017",
                f"{op.type} contracts `{op.input('X')[0]}` (last dim "
                f"on {kx!r}) against `{op.input('Y')[0]}` (contract "
                f"dim on {ky!r}) — one side must be resharded before "
                f"the matmul", op=op, var=op.input("X")[0])
            senv.set_output(op, "Out", None)
            return
    out = None
    if x is not None and y is not None and len(x) >= 1 and len(y) >= 1:
        out = tuple(x[:-1]) + (y[-1] if len(y) >= 1 else None,)
    senv.set_output(op, "Out", out)


@sharding_rule("transpose", "transpose2")
def _s_transpose(op, senv):
    x = senv.input_spec(op, "X")
    perm = op.attr("axis") or op.attr("perm")
    out = None
    if x is not None and perm and len(perm) == len(x):
        out = tuple(x[p] for p in perm)
    senv.set_output(op, "Out", out)


@sharding_rule("reshape", "reshape2")
def _s_reshape(op, senv):
    senv.set_output(op, "Out", None)  # dim mapping unknown: stay silent


@sharding_rule("lookup_table")
def _s_lookup_table(op, senv):
    # a vocab-sharded table gathers over the mesh (GSPMD's all-to-all,
    # the pserver prefetch analog) — the rows coming OUT follow the ids
    ids = senv.input_spec(op, "Ids")
    out = None
    if ids is not None:
        out = tuple(ids) + (None,)
    senv.set_output(op, "Out", out)


@sharding_rule("merge_selected_rows", "get_tensor_from_selected_rows")
def _s_selected_rows_unary(op, senv):
    # row-set transforms: the logical [height, dim] layout (and thus
    # the placement) carries through unchanged
    senv.set_output(op, "Out", senv.input_spec(op, "X"))


@sharding_rule("sgd", "momentum", "adam", "adamax", "adagrad",
               "rmsprop", "decayed_adagrad", "adadelta", "ftrl")
def _s_optimizer(op, senv):
    p = senv.input_spec(op, "Param")
    g = senv.input_spec(op, "Grad")
    if p is not None and g is not None and p != g:
        senv.report(
            "PTA016",
            f"{op.type} updates `{op.input('Param')[0]}` (placed {p}) "
            f"with a gradient placed {g} — param and grad shardings "
            f"must agree", op=op, var=op.input("Param")[0])
    # ZeRO discipline: every param-shaped state slot of ONE update op
    # must share one placement — a plan that shards moment1 but leaves
    # moment2 replicated (or splits them over different axes) computes
    # the update across misaligned slices.  Params replicated + state
    # sharded is the *intended* ZeRO shape, so param-vs-state
    # disagreement stays silent; only state-vs-state is provably wrong.
    from paddle_tpu.parallel.zero import OPTIMIZER_STATE_SLOTS
    known = []
    for slot in OPTIMIZER_STATE_SLOTS.get(op.type, ()):
        if not op.input(slot):
            continue
        spec = senv.input_spec(op, slot)
        if spec is not None:
            known.append((slot, op.input(slot)[0], spec))
    for (a_slot, a_name, a_spec), (b_slot, b_name, b_spec) in \
            zip(known, known[1:]):
        if a_spec != b_spec:
            senv.report(
                "PTA016",
                f"{op.type} optimizer state is inconsistently sharded: "
                f"`{a_name}` ({a_slot}) placed {a_spec} but `{b_name}` "
                f"({b_slot}) placed {b_spec} — all state slots of one "
                f"update must share a placement (the ZeRO plan owns "
                f"them together)", op=op, var=b_name)
    senv.set_output(op, "ParamOut", p)


# ---------------------------------------------------------------------------
# gen bundle: recompile hazards (PTA018) + signature drift (PTA019)
# ---------------------------------------------------------------------------

def check_gen_bundle(prefill, decode, meta):
    """``prefill``/``decode``: ``(program, feed_names, fetch_names)``;
    ``meta``: the parsed ``gen_meta.json``.  Proves the
    constant-jit-key contract of the pair."""
    def _names(targets):
        return None if targets is None else \
            [getattr(t, "name", t) for t in targets]

    diags = []
    pre_prog, pre_feeds, pre_fetches = prefill
    dec_prog, dec_feeds, dec_fetches = decode
    pre_feeds, pre_fetches = _names(pre_feeds), _names(pre_fetches)
    dec_feeds, dec_fetches = _names(dec_feeds), _names(dec_fetches)
    cache_vars = list(meta.get("cache_vars") or ())
    num_slots = meta.get("num_slots")
    max_len = meta.get("max_len")
    page_len = meta.get("page_len")
    paged = page_len is not None
    num_pages = meta.get("num_pages")
    pt_feed = meta.get("page_table_feed", "gen_page_table")
    pages_per_slot = None
    if paged and max_len is not None and int(page_len) > 0:
        pages_per_slot = -(-int(max_len) // int(page_len))

    # -- PTA018: prompt buckets must be sane and inside the cache ------
    buckets = list(meta.get("prompt_buckets") or ())
    if not buckets:
        diags.append(Diagnostic(
            "PTA018",
            "gen bundle declares no prompt_buckets — every distinct "
            "prompt length compiles a fresh prefill executable",
            program="gen_meta"))
    else:
        if any(b2 <= b1 for b1, b2 in zip(buckets, buckets[1:])):
            diags.append(Diagnostic(
                "PTA018",
                f"prompt_buckets {buckets} are not strictly "
                f"increasing — row_bucket's edge walk needs sorted "
                f"edges, so lookups past the disorder fall off the "
                f"declared (warmed) ladder", program="gen_meta"))
        if max_len is not None and buckets[-1] > int(max_len):
            diags.append(Diagnostic(
                "PTA018",
                f"largest prompt bucket {buckets[-1]} exceeds the "
                f"cache length {max_len} — the bucket is declared but "
                f"never warmed (warmup skips it), so a prompt landing "
                f"there compiles at request time",
                program="gen_meta"))

    # -- PTA018: page buckets — the paged decode jit-signature ladder --
    if paged:
        pbuckets = list(meta.get("page_buckets") or ())
        if not pbuckets:
            diags.append(Diagnostic(
                "PTA018",
                "paged gen bundle declares no page_buckets — every "
                "distinct live page count compiles a fresh decode "
                "executable", program="gen_meta"))
        else:
            if any(b2 <= b1 for b1, b2 in zip(pbuckets, pbuckets[1:])):
                diags.append(Diagnostic(
                    "PTA018",
                    f"page_buckets {pbuckets} are not strictly "
                    f"increasing — row_bucket's edge walk needs sorted "
                    f"edges, so lookups past the disorder fall off the "
                    f"declared (warmed) ladder", program="gen_meta"))
            if pages_per_slot is not None and \
                    pbuckets[-1] < pages_per_slot:
                diags.append(Diagnostic(
                    "PTA018",
                    f"largest page bucket {pbuckets[-1]} covers only "
                    f"{pbuckets[-1] * int(page_len)} of max_len "
                    f"{max_len} — a slot growing past it escapes the "
                    f"declared (warmed) ladder and compiles at request "
                    f"time", program="gen_meta"))
            if pages_per_slot is not None and \
                    pbuckets[-1] > pages_per_slot:
                diags.append(Diagnostic(
                    "PTA018",
                    f"largest page bucket {pbuckets[-1]} exceeds the "
                    f"per-slot page count {pages_per_slot} — the "
                    f"bucket is declared (and warmed) but no slot can "
                    f"ever reach it", program="gen_meta"))

    # -- PTA019: decode signature must be constant ---------------------
    # (the paged page-table feed is the ONE sanctioned dynamic dim: its
    # width is bucketed by the predictor, so the jit key is the bucket)
    dec_block = dec_prog.global_block()
    for name in dec_feeds or ():
        shape, _ = _var_meta(dec_block, name)
        if paged and name == pt_feed:
            if shape is not None and len(shape) == 2 and \
                    num_slots is not None and shape[0] != int(num_slots):
                diags.append(Diagnostic(
                    "PTA019",
                    f"page-table feed `{name}` is {shape} but must "
                    f"carry one row per slot "
                    f"(num_slots={num_slots})", var=name,
                    program="decode"))
            continue
        if shape is None or any(d < 0 for d in shape):
            diags.append(Diagnostic(
                "PTA019",
                f"decode feed `{name}` has dynamic shape "
                f"{shape} — every decode step must share ONE jit "
                f"signature; admission/eviction would recompile",
                var=name, program="decode"))
    if paged and pt_feed not in (dec_feeds or ()):
        diags.append(Diagnostic(
            "PTA019",
            f"paged gen bundle's decode program does not feed "
            f"`{pt_feed}` — page-bucketed decode cannot address the "
            f"pool", var=pt_feed, program="decode"))
    if paged and num_pages is not None and pages_per_slot is not None \
            and int(num_pages) < pages_per_slot:
        diags.append(Diagnostic(
            "PTA019",
            f"page pool has {num_pages} page(s) but one full-length "
            f"slot needs {pages_per_slot} — a single request hitting "
            f"max_len {max_len} cannot be served", program="gen_meta"))

    # -- PTA019: cache tensors must match the meta geometry ------------
    for name in cache_vars:
        if not dec_block.has_var(name):
            diags.append(Diagnostic(
                "PTA019",
                f"gen_meta names cache var `{name}` but the decode "
                f"program does not declare it", var=name,
                program="decode"))
            continue
        v = dec_block.var(name)
        if not getattr(v, "persistable", False):
            diags.append(Diagnostic(
                "PTA019",
                f"cache var `{name}` is not persistable in the decode "
                f"program — the KV pool would not live across steps",
                var=name, program="decode"))
        shape, _ = _var_meta(dec_block, name)
        if paged:
            if shape is not None and num_pages is not None and \
                    len(shape) >= 2 and \
                    (shape[0] != int(num_pages) or
                     shape[1] != int(page_len)):
                diags.append(Diagnostic(
                    "PTA019",
                    f"cache var `{name}` is {shape} but gen_meta "
                    f"declares [num_pages={num_pages}, "
                    f"page_len={page_len}, ...] — the bundle drifted "
                    f"between export and meta",
                    var=name, program="decode"))
        elif shape is not None and num_slots is not None and \
                max_len is not None and len(shape) >= 2 and \
                (shape[0] != int(num_slots) or shape[1] != int(max_len)):
            diags.append(Diagnostic(
                "PTA019",
                f"cache var `{name}` is {shape} but gen_meta declares "
                f"[num_slots={num_slots}, max_len={max_len}, ...] — "
                f"the bundle drifted between export and meta",
                var=name, program="decode"))

    # -- PTA019: prefill fetch list must seed exactly the cache --------
    if cache_vars and pre_fetches is not None:
        want = 1 + len(cache_vars)  # logits + per-layer K/V
        if len(pre_fetches) != want:
            diags.append(Diagnostic(
                "PTA019",
                f"prefill fetches {len(pre_fetches)} value(s) but the "
                f"decode cache needs {want} (logits + "
                f"{len(cache_vars)} K/V tensors) — the prefill/decode "
                f"signatures drifted", program="prefill"))
        else:
            pre_block = pre_prog.global_block()
            for fetch_name, cache_name in zip(pre_fetches[1:],
                                              cache_vars):
                f_shape, _ = _var_meta(pre_block, fetch_name)
                c_shape, _ = _var_meta(dec_block, cache_name)
                if f_shape is not None and c_shape is not None and \
                        f_shape[-1] > 0 and c_shape[-1] > 0 and \
                        f_shape[-1] != c_shape[-1]:
                    diags.append(Diagnostic(
                        "PTA019",
                        f"prefill K/V fetch `{fetch_name}` has feature "
                        f"dim {f_shape[-1]} but cache `{cache_name}` "
                        f"expects {c_shape[-1]} — seeding the slot "
                        f"would write misshapen rows",
                        var=fetch_name, program="prefill"))
    return diags


def load_saved_program(target):
    """(program, feed_names, fetch_names) from a save_inference_model
    dir (its ``__model__``) or a ``__model__`` json file — the shared
    static loader behind every ``paddle_tpu lint`` target (no params,
    no executor).  Raises the underlying OSError/ValueError/KeyError
    on a malformed target; callers map those to exit code 2."""
    path = os.path.join(target, "__model__") \
        if os.path.isdir(target) else target
    with open(path) as f:
        model = json.load(f)
    from paddle_tpu.framework import Program
    return (Program.from_dict(model["program"]),
            model.get("feed_var_names"), model.get("fetch_var_names"))


def lint_gen_bundle(dirname):
    """Multi-program lint of an exported generation bundle
    (``<dirname>/prefill``, ``<dirname>/decode``, ``gen_meta.json``):
    each program through the full single-program lint, plus the
    cross-program PTA018/PTA019 checks.  Returns a list of
    ``(label, AnalysisResult)`` plus a cross-check AnalysisResult."""
    from paddle_tpu.analysis.analyzer import AnalysisResult, lint_program

    with open(os.path.join(dirname, "gen_meta.json")) as f:
        meta = json.load(f)
    prefill = load_saved_program(os.path.join(dirname, "prefill"))
    decode = load_saved_program(os.path.join(dirname, "decode"))
    results = [
        ("prefill", lint_program(prefill[0], feed_names=prefill[1],
                                 fetch_names=prefill[2])),
        ("decode", lint_program(decode[0], feed_names=decode[1],
                                fetch_names=decode[2])),
        ("bundle", AnalysisResult(check_gen_bundle(prefill, decode,
                                                   meta))),
    ]
    return results


def verify_gen_bundle(dirname, where="gen.export"):
    """Raising form of :func:`lint_gen_bundle` — the post-export
    self-check ``export_gen_model`` runs, so a drifted bundle fails at
    export, not at the first ``/generate``.  Error-severity findings
    (PTA019 drift) raise; warning-severity recompile hazards (PTA018)
    are logged at warning level — the bundle works, but the operator
    should see the hazard at export time, not in a latency dashboard."""
    import logging

    errors = []
    for label, result in lint_gen_bundle(dirname):
        errors.extend(result.errors)
        for d in result.warnings:
            logging.getLogger(__name__).warning(
                "gen bundle %s: [%s] %s", dirname, label, d.format())
    if errors:
        raise ProgramVerificationError(errors, where=where)
    return errors


def lint_pipeline(program, n_stages, feed_names, fetch_names):
    """Multi-program lint of one program's pipeline split: run the
    single-program lint, split into stages, and validate the stage set
    (boundary carriers, cross-stage collectives, i32 carrier lanes).
    Returns an AnalysisResult."""
    from paddle_tpu.analysis.analyzer import (AnalysisResult,
                                              check_pipeline_carriers)
    from paddle_tpu.parallel.pipeline_transpiler import split_program

    block, stage_ops, _stage_params, boundaries = split_program(
        program, n_stages, list(feed_names or ()),
        list(fetch_names or ()))
    diags = check_stage_set(block, stage_ops, boundaries,
                            feed_names=feed_names or ())
    try:
        check_pipeline_carriers(block, boundaries)
    except ProgramVerificationError as e:
        diags.extend(e.diagnostics)
    return AnalysisResult(diags)


def lint_pair(trainer, pservers):
    """Multi-program lint of a transpiled trainer/pserver family:
    Send/Recv pairing + split reassembly.  ``trainer``/``pservers``
    entries are ``(label, program)``.

    Collective matching is deliberately NOT run here: trainer and
    pserver are different ROLES, not SPMD peers — a trainer's gradient
    all-reduce rendezvouses with the other trainers, never with the
    pserver, so requiring matching sequences across the pair would be
    a guaranteed false positive.  Collective matching applies to
    homogeneous families only (replicas of one role, pipeline stages):
    :func:`check_collective_match` / :func:`check_pipeline_stages`."""
    from paddle_tpu.analysis.analyzer import AnalysisResult

    return AnalysisResult(check_transpiled_pair(trainer, pservers))
