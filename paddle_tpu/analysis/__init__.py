"""Static analysis over the Program IR (the verify-before-compile half
of the fault story — ``docs/static_analysis.md``).

The TPU build lowers a whole block to one XLA computation, so a
malformed program otherwise surfaces as a cryptic trace error deep in
``Executor.run`` — or runs silently wrong.  This package proves what it
can BEFORE tracing:

* :mod:`~paddle_tpu.analysis.structural` — def-before-use across
  nested control-flow blocks, feed/fetch targets, persistable
  re-definition (PTA001–PTA004);
* :mod:`~paddle_tpu.analysis.typecheck` — per-op shape/dtype inference
  rules with a warn-list for uncovered ops (PTA005, PTA006, PTA010);
* :mod:`~paddle_tpu.analysis.lints` — dead ops, unused feeds,
  donation/aliasing hazards (PTA007–PTA009).

Entry points: ``lint_program`` (everything; ``paddle_tpu lint``),
``verify_program`` (structural, raising — the ``PADDLE_TPU_VERIFY=1``
executor hook), ``verify_transpiled`` (the post-rewrite contract every
transpiler calls).
"""

from paddle_tpu.analysis.analyzer import (AnalysisResult, analyze_program,
                                          check_pipeline_carriers,
                                          lint_program, verify_program,
                                          verify_transpiled)
from paddle_tpu.analysis.diagnostics import (DIAGNOSTIC_CODES, Diagnostic,
                                             ProgramVerificationError,
                                             format_diagnostics)
from paddle_tpu.analysis import typecheck

__all__ = [
    "AnalysisResult", "analyze_program", "lint_program", "verify_program",
    "verify_transpiled", "check_pipeline_carriers", "DIAGNOSTIC_CODES",
    "Diagnostic", "ProgramVerificationError", "format_diagnostics",
    "typecheck",
]
