"""Static analysis over the Program IR (the verify-before-compile half
of the fault story — ``docs/static_analysis.md``).

The TPU build lowers a whole block to one XLA computation, so a
malformed program otherwise surfaces as a cryptic trace error deep in
``Executor.run`` — or runs silently wrong.  This package proves what it
can BEFORE tracing:

* :mod:`~paddle_tpu.analysis.structural` — def-before-use across
  nested control-flow blocks, feed/fetch targets, persistable
  re-definition (PTA001–PTA004);
* :mod:`~paddle_tpu.analysis.typecheck` — per-op shape/dtype inference
  rules with a warn-list for uncovered ops (PTA005, PTA006, PTA010);
* :mod:`~paddle_tpu.analysis.lints` — dead ops, unused feeds,
  donation/aliasing hazards (PTA007–PTA009);
* :mod:`~paddle_tpu.analysis.distributed` — cross-program verifier for
  the families a transpile produces: collective matching, Send/Recv
  pairing, split reassembly, stage boundary agreement, sharding-spec
  propagation, recompile hazards (PTA011–PTA019);
* :mod:`~paddle_tpu.analysis.opmeta` — the SHARED op-metadata registry
  (pure/effectful/stateful/sub-block classification) the lints, the
  optimization passes, and the cost model all ride;
* :mod:`~paddle_tpu.analysis.cost` — static per-op FLOPs/bytes cost
  model (``@cost.rule`` functions over the typecheck shape inference);
* :mod:`~paddle_tpu.analysis.opt` — the verify-sandwiched optimization
  pass pipeline (``PADDLE_TPU_OPT=1``, ``paddle_tpu opt``): constant
  folding, CSE, DCE, elementwise fusion, the donation planner, and the
  cost-model compile-amortization gate;
* :mod:`~paddle_tpu.analysis.visualize` — GraphViz DOT rendering of a
  Program (blocks as clusters, donation/creation-site annotations;
  ``paddle_tpu lint --dot out.dot``) and pseudo-code pretty printing.

Entry points: ``lint_program`` (everything; ``paddle_tpu lint``),
``verify_program`` (structural, raising — the ``PADDLE_TPU_VERIFY=1``
executor hook), ``verify_transpiled`` (the post-rewrite contract every
transpiler calls), and the multi-program units ``lint_gen_bundle`` /
``lint_pipeline`` / ``lint_pair`` (``paddle_tpu lint``'s gen-bundle,
``--pipeline``, and ``--pair`` modes).
"""

from paddle_tpu.analysis.analyzer import (AnalysisResult, analyze_program,
                                          check_pipeline_carriers,
                                          lint_program, verify_program,
                                          verify_transpiled)
from paddle_tpu.analysis.diagnostics import (DIAGNOSTIC_CODES, Diagnostic,
                                             ProgramVerificationError,
                                             format_diagnostics)
from paddle_tpu.analysis import typecheck
from paddle_tpu.analysis import distributed
from paddle_tpu.analysis import cost
from paddle_tpu.analysis import opmeta
from paddle_tpu.analysis import visualize
from paddle_tpu.analysis.distributed import (check_distributed_spec,
                                             check_gen_bundle,
                                             check_stage_set,
                                             check_transpiled_pair,
                                             lint_gen_bundle, lint_pair,
                                             lint_pipeline,
                                             verify_gen_bundle)

__all__ = [
    "AnalysisResult", "analyze_program", "lint_program", "verify_program",
    "verify_transpiled", "check_pipeline_carriers", "DIAGNOSTIC_CODES",
    "Diagnostic", "ProgramVerificationError", "format_diagnostics",
    "typecheck", "distributed", "cost", "opmeta", "visualize",
    "check_distributed_spec",
    "check_gen_bundle", "check_stage_set", "check_transpiled_pair",
    "lint_gen_bundle", "lint_pair", "lint_pipeline", "verify_gen_bundle",
]
