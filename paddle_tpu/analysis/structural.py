"""Structural verifier: def-before-use, feed/fetch targets, persistable
re-definition.

This is the pass ``PADDLE_TPU_VERIFY=1`` runs before first compile and
the one every transpiler (``append_backward``, parallel/pipeline/memory
rewrites) runs after mutating a program — it needs no shape or dtype
knowledge, only the op list and the symbol tables, so it is cheap
(one walk over the ops) and has no false positives by construction:

* **PTA001** — an op input that is declared NOWHERE in the block's
  scope chain and produced by no op.  (Outputs are auto-declared by
  ``append_op``, so an undeclared read can only come from a broken
  hand-built rewrite — exactly what post-transpile verification exists
  to catch.)
* **PTA002** — an op input whose FIRST write in the same block comes
  after the reading op (reading scratch before it exists).  Declared
  vars that are never written at all are implicit feeds / scope state
  (the executor reads them from the scope) and stay silent.  Sub-blocks
  of loop ops (``while``/``recurrent``) are checked leniently: a loop
  body legitimately reads this-iteration values written later in the
  body (the carry), so every name the body writes counts as defined.
* **PTA003** — a requested feed/fetch name that resolves to no variable
  and no op output.
* **PTA004** — a persistable/parameter var overwritten by an op that
  neither reads it (the in-place self-update idiom: optimizers,
  batch_norm running stats) nor declares the slot in its opdef's
  ``stateful_outputs``, AFTER an earlier op already read the var.  A
  startup program initializing params (write, no prior read) is fine;
  a step program clobbering a param it already consumed is not.
"""

from __future__ import annotations

from paddle_tpu import framework
from paddle_tpu.analysis.diagnostics import Diagnostic

__all__ = ["check_structure"]

# sub-blocks of these op types carry loop semantics: a read inside the
# body may be satisfied by a later write in the SAME body (previous
# iteration's value) — def-before-use is checked leniently there
_LOOP_OP_TYPES = frozenset({"while", "recurrent", "while_grad",
                            "recurrent_grad"})


def _sub_blocks(op):
    for a in op.attrs.values():
        if isinstance(a, framework.Block):
            yield a


def _reads_of(op):
    """Input names of ``op`` plus every outer-name read inside its
    sub-blocks that the sub-blocks themselves do not produce."""
    reads = [n for n in op.input_arg_names if n]
    for blk in _sub_blocks(op):
        reads.extend(_external_reads(blk))
    return reads


def _external_reads(block):
    produced = set()
    ext = []
    for op in block.ops:
        for n in op.input_arg_names:
            if n and n not in produced and not block.has_var_local(n):
                ext.append(n)
        for n in op.output_arg_names:
            if n:
                produced.add(n)
        for sub in _sub_blocks(op):
            ext.extend(n for n in _external_reads(sub)
                       if n not in produced)
    return ext


def _declared(block, name):
    try:
        block.var(name)
        return True
    except KeyError:
        return False


def _state_like(block, name):
    """Names the executor serves from the scope without a producing op:
    persistable state (params, optimizer moments, running stats),
    declared feeds (``is_data``), and runtime objects (readers,
    tensor arrays built by earlier programs)."""
    try:
        v = block.var(name)
    except KeyError:
        return False
    return bool(getattr(v, "persistable", False) or
                getattr(v, "is_data", False) or
                getattr(v, "type", "lod_tensor") in ("reader",
                                                     "tensor_array"))


def check_structure(program, feed_names=None, fetch_names=None):
    """Run the structural checks; returns a list of Diagnostics."""
    diags = []
    gblock = program.global_block()

    produced_anywhere = set()
    for blk in program.blocks:
        for op in blk.ops:
            produced_anywhere.update(n for n in op.output_arg_names if n)

    for kind, names in (("feed", feed_names or ()),
                        ("fetch", fetch_names or ())):
        for name in names:
            if not gblock.has_var(name) and name not in produced_anywhere:
                diags.append(Diagnostic(
                    "PTA003",
                    f"{kind} target `{name}` is not a variable of the "
                    f"program and no op produces it",
                    block_idx=0, var=name))

    _check_block(gblock, set(), diags, lenient=False)
    return diags


def _writes_in(block):
    names = set()
    for op in block.ops:
        names.update(n for n in op.output_arg_names if n)
        for sub in _sub_blocks(op):
            names.update(_writes_in(sub))
    return names


def _check_block(block, outer_defined, diags, lenient):
    defined = set(outer_defined)
    if lenient:
        defined |= _writes_in(block)

    # first write index per name (this block only) for PTA002 messages
    first_write = {}
    for i, op in enumerate(block.ops):
        for n in op.output_arg_names:
            if n and n not in first_write:
                first_write[n] = i

    read_before = {}   # name -> first op index that read it (PTA004)
    written_by = {}    # persistable name -> first non-self-update writer

    from paddle_tpu.ops import registry as _registry

    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        site = getattr(op, "creation_site", None)
        for n in _reads_of(op):
            read_before.setdefault(n, i)
            if n in defined or _state_like(block, n):
                continue
            if not _declared(block, n) and n not in first_write:
                diags.append(Diagnostic(
                    "PTA001",
                    f"op `{op.type}` reads `{n}`, which is declared "
                    f"nowhere in block {block.idx}'s scope chain and "
                    f"produced by no op",
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=n, site=site))
            elif first_write.get(n, -1) > i:
                diags.append(Diagnostic(
                    "PTA002",
                    f"op `{op.type}` reads `{n}` at op #{i}, but its "
                    f"first write is op #{first_write[n]} "
                    f"(`{block.ops[first_write[n]].type}`) — the value "
                    f"is undefined at the read",
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=n, site=site))
            # declared but never written: implicit feed/scope state

        opdef = _registry.lookup(op.type)
        stateful = opdef.stateful_outputs if opdef is not None else ()
        op_reads = set(op.input_arg_names)
        for slot, names in op.outputs.items():
            for n in names:
                if not n:
                    continue
                defined.add(n)
                if slot in stateful or n in op_reads:
                    continue  # declared in-place state update
                try:
                    v = block.var(n)
                except KeyError:
                    continue
                if not getattr(v, "persistable", False):
                    continue
                prior_read = read_before.get(n)
                if prior_read is not None and prior_read < i:
                    diags.append(Diagnostic(
                        "PTA004",
                        f"persistable `{n}` "
                        f"{'(parameter) ' if isinstance(v, framework.Parameter) else ''}"
                        f"is overwritten by op `{op.type}` at op #{i} "
                        f"after op #{prior_read} already read it — a "
                        f"step must not re-define its own state "
                        f"(declare the slot in stateful_outputs if this "
                        f"is an intended in-place update)",
                        block_idx=block.idx, op_index=i, op_type=op.type,
                        var=n, site=site))
                elif n in written_by:
                    diags.append(Diagnostic(
                        "PTA004",
                        f"persistable `{n}` is defined twice: op "
                        f"#{written_by[n]} and op #{i} (`{op.type}`) "
                        f"both overwrite it within one step",
                        block_idx=block.idx, op_index=i, op_type=op.type,
                        var=n, site=site))
                else:
                    written_by[n] = i

        for sub in _sub_blocks(op):
            _check_block(sub, defined, diags,
                         lenient=op.type in _LOOP_OP_TYPES)
