"""The optimization passes.

All passes mutate the program handed to them IN PLACE (the pipeline
clones first) and return a stats dict.  Eligibility always goes
through the shared op-metadata registry (``analysis/opmeta.py``) — the
same classification the dead-op lint exempts by, so a pass can never
delete what a lint protects.

RNG-slot bookkeeping: the executor derives each op's RNG key as
``fold_in(base_key, counter)`` where the counter advances one slot per
op in trace order.  A pass that removes or fuses ops must not shift
the counter positions of surviving RNG consumers (dropout masks would
silently change), so every removal charges its slots to the next
surviving op via the ``__rng_slots__`` attr — surviving ops fold the
EXACT key they would have folded in the unoptimized program, which is
what makes the golden-equivalence harness exact even for programs with
live dropout.
"""

from __future__ import annotations

import logging

import numpy as np

from paddle_tpu import framework
from paddle_tpu.analysis import opmeta
from paddle_tpu.analysis.structural import _external_reads, _sub_blocks
from paddle_tpu.framework import Operator

logger = logging.getLogger(__name__)

__all__ = ["PASS_REGISTRY", "PassContext", "constant_fold_pass",
           "cse_pass", "dce_pass", "fuse_elementwise_pass",
           "donation_plan_pass", "RNG_SLOTS_ATTR", "FUSED_OP_TYPE"]

RNG_SLOTS_ATTR = "__rng_slots__"
FUSED_OP_TYPE = "fused_elementwise"

#: largest element count a folded constant may embed in an op attr
MAX_FOLD_ELEMENTS = 4096

#: dtypes ``assign_value`` can carry losslessly through attr lists
_FOLDABLE_DTYPES = ("float32", "int32", "int64", "bool")


class PassContext:
    """What every pass may assume: the executor-declared feed/fetch
    names (roots the passes must preserve verbatim)."""

    def __init__(self, feed_names=(), fetch_names=()):
        self.feed_names = tuple(feed_names or ())
        self.fetch_names = tuple(fetch_names or ())


def _rng_slots(op):
    return int(op.attrs.get(RNG_SLOTS_ATTR, 1))


def _charge_slots(ops, removed_mask):
    """Fold the RNG slots of removed ops into the next surviving op
    (see module docstring); returns the surviving op list."""
    out = []
    pending = 0
    for op, removed in zip(ops, removed_mask):
        if removed:
            pending += _rng_slots(op)
            continue
        if pending:
            op.attrs[RNG_SLOTS_ATTR] = _rng_slots(op) + pending
            pending = 0
        out.append(op)
    return out


def _writer_counts(block):
    counts = {}
    for op in block.ops:
        for n in op.output_arg_names:
            if n:
                counts[n] = counts.get(n, 0) + 1
    return counts


def _sub_block_reads(block):
    """Every name read inside any sub-block of ``block``'s ops —
    renaming or removing producers of these is off-limits for the
    block-local passes."""
    reads = set()
    for op in block.ops:
        for sub in _sub_blocks(op):
            reads.update(_external_reads(sub))
    return reads


def _protected_names(block, ctx):
    """Names a pass may never orphan or rename away: fetch targets,
    feeds, persistables, and anything sub-blocks read."""
    names = set(ctx.fetch_names) | set(ctx.feed_names)
    for blk in block.program.blocks:
        for v in blk.vars.values():
            if getattr(v, "persistable", False):
                names.add(v.name)
    names |= _sub_block_reads(block)
    return names


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

def _const_of(op):
    """The literal an op provably produces, or None."""
    try:
        if op.type in ("fill_constant", "fill"):
            shape = op.attr("shape")
            dtype = str(op.attr("dtype", "float32"))
            if shape is None or any(int(d) < 0 for d in shape) or \
                    dtype not in _FOLDABLE_DTYPES:
                return None
            return np.full(tuple(int(d) for d in shape),
                           op.attr("value", 0.0), dtype=dtype)
        if op.type == "assign_value":
            shape = tuple(op.attr("shape"))
            dtype = str(op.attr("dtype", "float32"))
            if dtype not in _FOLDABLE_DTYPES:
                return None
            values = op.attr("fp32_values") if dtype.startswith("float") \
                else op.attr("int32_values")
            return np.asarray(values, dtype=dtype).reshape(shape)
    except (TypeError, ValueError, OverflowError):
        return None
    return None


def _evaluate_host(op, block, const_env):
    """Host-evaluate one pure op over concrete numpy inputs via its
    registered lowering (exact semantics — the same code the executor
    traces), returning the output ndarray or None."""
    from paddle_tpu.ops import registry
    opdef = registry.lookup(op.type)
    if opdef is None or opdef.lower is None:
        return None
    env = {n: const_env[n] for n in op.input_arg_names if n}
    ctx = registry.LowerContext(op, env, block, rng_key=None,
                                training=False, aux={})
    try:
        opdef.lower(ctx)
    except Exception:
        return None
    outs = op.output("Out")
    if len(outs) != 1 or outs[0] not in ctx.outputs:
        return None
    return np.asarray(ctx.outputs[outs[0]])


def _assign_value_op(block, name, value):
    dtype = str(value.dtype)
    if dtype.startswith("float"):
        attrs = {"fp32_values": [float(v) for v in value.ravel()]}
    else:
        attrs = {"int32_values": [int(v) for v in value.ravel()]}
    attrs["shape"] = [int(d) for d in value.shape]
    attrs["dtype"] = dtype
    return Operator(block, "assign_value", {}, {"Out": [name]}, attrs)


def constant_fold_pass(program, ctx):
    """Fold chains of pure ops rooted in literal producers
    (``fill_constant``/``assign_value``) by evaluating them host-side
    and replacing each with a single ``assign_value`` carrying the
    result — shape-arithmetic scaffolding compiles to data instead of
    HLO.  Folded-away producers become dead and fall to the DCE pass."""
    from paddle_tpu.ops import registry
    block = program.global_block()
    const_env = {}
    folded = 0
    new_ops = []
    for op in block.ops:
        value = _const_of(op)
        if value is not None:
            for n in op.output("Out"):
                const_env[n] = value
            new_ops.append(op)
            continue
        eligible = (
            op.type in opmeta.ELEMENTWISE_PURE_OPS | {
                "reshape", "reshape2", "transpose", "transpose2",
                "concat"}
            and opmeta.is_pure(op, block, registry)
            and not opmeta.has_sub_block(op)
            and len(op.output("Out")) == 1
            and all(n in const_env for n in op.input_arg_names if n)
            and op.input_arg_names)
        if eligible:
            out_name = op.output("Out")[0]
            result = _evaluate_host(op, block, const_env)
            if result is not None and result.size <= MAX_FOLD_ELEMENTS \
                    and str(result.dtype) in _FOLDABLE_DTYPES \
                    and _int_fits(result):
                const_env[out_name] = result
                rep = _assign_value_op(block, out_name, result)
                rep.attrs[RNG_SLOTS_ATTR] = _rng_slots(op)
                folded += 1
                new_ops.append(rep)
                continue
        # any other write invalidates a tracked constant: a later
        # consumer must not fold the stale value
        for n in op.output_arg_names:
            const_env.pop(n, None)
        new_ops.append(op)
    if folded:
        block.ops[:] = new_ops
        program.bump_version()
        from paddle_tpu import profiler as _profiler
        _profiler.runtime_metrics.inc("opt.constants_folded", folded)
        # folding orphans the chains' producers (their values now live
        # in attrs) — sweep them here so this pass leaves no dead ops
        # behind (the verify-sandwich would rightly reject a pass that
        # INTRODUCES PTA007 findings)
        swept = dce_pass(program, ctx)
        return {"folded": folded, "swept": swept["removed"]}
    return {"folded": folded}


def _int_fits(value):
    """int64 results must survive the int32-valued attr round-trip
    (the same contract PTA010 lints)."""
    if value.dtype != np.int64:
        return True
    if value.size == 0:
        return True
    return bool(value.max() <= np.iinfo(np.int32).max and
                value.min() >= np.iinfo(np.int32).min)


# ---------------------------------------------------------------------------
# common subexpression elimination
# ---------------------------------------------------------------------------

def _attr_key(attrs):
    parts = []
    for k in sorted(attrs):
        if k == RNG_SLOTS_ATTR:
            continue
        v = attrs[k]
        if isinstance(v, framework.Block):
            return None  # sub-block ops are never CSE candidates
        if isinstance(v, np.ndarray):
            parts.append((k, "nd", str(v.dtype), v.shape,
                          v.tobytes()))
        elif isinstance(v, (list, tuple)):
            parts.append((k, tuple(map(repr, v))))
        else:
            parts.append((k, repr(v)))
    return tuple(parts)


def cse_pass(program, ctx):
    """Deduplicate pure ops with identical ``(type, inputs, attrs)``:
    the later op is dropped and its consumers read the earlier op's
    outputs.  Only single-writer names participate (renaming is unsafe
    off SSA), and protected names (fetches, feeds, persistables,
    sub-block reads) are never renamed away."""
    from paddle_tpu.ops import registry
    block = program.global_block()
    writers = _writer_counts(block)
    protected = _protected_names(block, ctx)
    # names any op updates in place: two reads of such a name at
    # different program points may see different values, so ops reading
    # them never dedupe (value identity cannot be keyed by name)
    inplace = set()
    for op in block.ops:
        inplace.update(opmeta.stateful_output_names(op, registry))
    seen = {}        # key -> canonical op
    rename = {}      # dropped name -> canonical name
    removed_mask = []
    deduped = 0
    for op in block.ops:
        # apply pending renames to this op's reads first
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]
        ok = (opmeta.is_pure(op, block, registry)
              and not opmeta.has_sub_block(op)
              and not opmeta.uses_rng(op, registry)
              and op.output_arg_names
              and all(writers.get(n, 0) == 1 and n not in protected
                      for n in op.output_arg_names if n)
              and all(writers.get(n, 0) <= 1 and n not in inplace
                      for n in op.input_arg_names if n))
        if not ok:
            removed_mask.append(False)
            continue
        akey = _attr_key(op.attrs)
        if akey is None:
            removed_mask.append(False)
            continue
        key = (op.type,
               tuple(sorted((s, tuple(ns))
                            for s, ns in op.inputs.items())),
               akey)
        canon = seen.get(key)
        if canon is None:
            seen[key] = op
            removed_mask.append(False)
            continue
        # same slot layout guaranteed by the key; map name -> name
        for slot, names in op.outputs.items():
            for old, new in zip(names, canon.output(slot)):
                if old and new:
                    rename[old] = new
        deduped += 1
        removed_mask.append(True)
    if deduped:
        block.ops[:] = _charge_slots(block.ops, removed_mask)
        program.bump_version()
    return {"deduped": deduped}


# ---------------------------------------------------------------------------
# dead code elimination
# ---------------------------------------------------------------------------

def dce_pass(program, ctx):
    """Remove provably dead ops: reverse liveness from the fetch
    targets and persistable writes, keeping everything the shared
    metadata registry classifies as effectful.  Unlike the PTA007 lint
    (which exempts unconsumed pure ``@GRAD`` chains because callers
    fetch grad vars ad hoc), this pass KNOWS the fetch list — autodiff
    chains nothing fetches are exactly the ops XLA would trace, lower,
    and DCE at compile time; removing them here is where the cold-start
    win comes from."""
    from paddle_tpu.ops import registry
    block = program.global_block()
    ops = block.ops
    needed = set(ctx.fetch_names)
    for blk in program.blocks:
        for v in blk.vars.values():
            if getattr(v, "persistable", False):
                needed.add(v.name)
    live = [False] * len(ops)
    for i in range(len(ops) - 1, -1, -1):
        op = ops[i]
        outs = [n for n in op.output_arg_names if n]
        if opmeta.has_effects(op, registry) or \
                any(n in needed for n in outs):
            live[i] = True
            needed.update(n for n in op.input_arg_names if n)
            for sub in _sub_blocks(op):
                needed.update(_external_reads(sub))
    removed = live.count(False)
    if removed:
        block.ops[:] = _charge_slots(ops, [not l for l in live])
        program.bump_version()
    return {"removed": removed}


# ---------------------------------------------------------------------------
# elementwise-chain fusion
# ---------------------------------------------------------------------------

def fuse_elementwise_pass(program, ctx):
    """Collapse maximal runs of ADJACENT pure elementwise ops — each
    intermediate consumed only by the next op in the run — into one
    ``fused_elementwise`` op whose lowering replays the member
    lowerings inside a single traced closure: one op's worth of
    per-op trace overhead (named_scope, context, RNG slot) instead of
    k, with identical array semantics (the member lowerings ARE the
    semantics)."""
    from paddle_tpu.ops import registry
    block = program.global_block()
    ops = block.ops
    writers = _writer_counts(block)
    protected = _protected_names(block, ctx)

    consumers = {}   # name -> list of op indices reading it
    for i, op in enumerate(ops):
        for n in op.input_arg_names:
            if n:
                consumers.setdefault(n, []).append(i)

    def fusable(op):
        return (op.type in opmeta.ELEMENTWISE_PURE_OPS
                and opmeta.is_pure(op, block, registry)
                and not opmeta.has_sub_block(op)
                and len(op.output_arg_names) == 1
                and len(op.output("Out")) == 1)

    def internal(i):
        """Op i's output may vanish inside a fusion: single writer,
        consumed exactly by op i+1, protected nowhere."""
        out = ops[i].output("Out")[0]
        return (writers.get(out, 0) == 1 and out not in protected
                and set(consumers.get(out, [-1])) == {i + 1})

    new_ops = []
    fused = 0
    fused_members = 0
    i = 0
    while i < len(ops):
        if not fusable(ops[i]):
            new_ops.append(ops[i])
            i += 1
            continue
        j = i
        while j + 1 < len(ops) and fusable(ops[j + 1]) and internal(j):
            j += 1
        if j == i:
            new_ops.append(ops[i])
            i += 1
            continue
        run = ops[i:j + 1]
        internal_names = {op.output("Out")[0] for op in run[:-1]}
        ext_inputs = []
        for op in run:
            for n in op.input_arg_names:
                if n and n not in internal_names and \
                        n not in ext_inputs:
                    ext_inputs.append(n)
        out_name = run[-1].output("Out")[0]
        fop = Operator(block, FUSED_OP_TYPE,
                       {"X": ext_inputs}, {"Out": [out_name]},
                       {"sub_ops": [op.to_dict() for op in run],
                        RNG_SLOTS_ATTR: sum(_rng_slots(op)
                                            for op in run)})
        new_ops.append(fop)
        fused += 1
        fused_members += len(run)
        i = j + 1
    if fused:
        block.ops[:] = new_ops
        program.bump_version()
        from paddle_tpu import profiler as _profiler
        _profiler.runtime_metrics.inc("opt.ops_fused", fused_members)
    return {"chains": fused, "members": fused_members}


# ---------------------------------------------------------------------------
# donation/aliasing planner
# ---------------------------------------------------------------------------

def donation_plan_pass(program, ctx):
    """Attach the donation/aliasing plan
    (``memory_optimization_transpiler.plan_donation``): which feed
    buffers die inside the step (donatable), which vars are declared
    in-place updates (``stateful_outputs`` facts the executor's
    donation path relies on) — each fact proven safe by the PTA009
    donation-hazard lint before it enters the plan.  Pure fact
    emission: the op list is untouched."""
    from paddle_tpu.memory_optimization_transpiler import plan_donation
    plan = plan_donation(program, feed_names=ctx.feed_names,
                         fetch_names=ctx.fetch_names)
    return {"donatable_feeds": len(plan.donatable_feeds),
            "inplace_updates": len(plan.inplace_updates),
            "hazards_dropped": len(plan.dropped)}


# ---------------------------------------------------------------------------
# compile-amortization gate
# ---------------------------------------------------------------------------

#: static-FLOPs ceiling under which a run-once program's XLA compile
#: can never pay for itself: an initializer interprets in milliseconds
#: while its compile costs hundreds — see docs/performance.md
AMORTIZE_FLOPS_CEILING = int(1e7)

#: op-count floor for choosing interpret over compile: eager execution
#: pays a fixed per-process warmup (first-use per-(primitive, shape)
#: dispatch compiles, ~0.4s measured on the CPU backend) while whole-
#: program XLA compile scales ~25ms/op vs ~7ms/op eager marginal cost —
#: break-even lands at ~25-45 ops, so only programs comfortably past
#: it take the interpret path (a 31-op mnist startup stays compiled;
#: a 64-op transformer startup interprets and saves ~1.5s)
AMORTIZE_MIN_OPS = 48


def amortize_pass(program, ctx):
    """Decide — from the static cost model — whether this program
    should be INTERPRETED instead of compiled: a program with no feeds
    and no fetches is structurally a run-once initializer (startup
    programs: every op exists to write persistable state), and when
    its total static FLOPs sit under :data:`AMORTIZE_FLOPS_CEILING`
    the XLA compile (hundreds of ms — 34–51%% of the zoo's measured
    cold start) buys nothing an eager op-by-op run doesn't deliver in
    milliseconds.  JAX's PRNG is deterministic across eager and
    compiled execution, so initial parameter values are unchanged.
    Attaches ``program._opt_interpret``; the op list is untouched."""
    if ctx.fetch_names or ctx.feed_names:
        return {"interpret": 0}
    block = program.global_block()
    if len(block.ops) < AMORTIZE_MIN_OPS:
        return {"interpret": 0}
    reads = {n for op in block.ops for n in op.input_arg_names if n}
    for v in block.vars.values():
        if getattr(v, "is_data", False) and v.name in reads:
            # a program consuming declared data is a step program,
            # whatever its fetch list says
            return {"interpret": 0}
    from paddle_tpu.analysis import cost
    est = cost.estimate(program)
    if est.total_flops > AMORTIZE_FLOPS_CEILING:
        return {"interpret": 0, "flops": est.total_flops}
    program._opt_interpret = True
    from paddle_tpu import profiler as _profiler
    _profiler.runtime_metrics.inc("opt.compiles_avoided")
    return {"interpret": 1, "flops": est.total_flops}


PASS_REGISTRY = {
    "constant_fold": constant_fold_pass,
    "cse": cse_pass,
    "dce": dce_pass,
    "fuse_elementwise": fuse_elementwise_pass,
    "donation_plan": donation_plan_pass,
    "amortize": amortize_pass,
}
