"""Program-IR optimization passes: the analyzer turned compiler
mid-layer (``docs/static_analysis.md`` "Optimization passes").

PR 7/9 built dataflow shape/dtype/sharding inference over the IR to
*check* programs; this package uses the same plumbing to *transform*
them ahead of XLA — shrinking the op count the executor traces and the
HLO the backend compiles (the cold-start cost the persistent compile
cache merely amortizes), and attaching statically proven facts (the
donation plan, the RNG-key plan) the executor exploits at trace time.

Every pass runs inside a **verify-sandwich**: the full analyzer
(structure + types + lints) runs before the pipeline and after every
pass, with the PTA codes as invariants — any diagnostic a pass
*introduces* aborts that pass and the program reverts to its pre-pass
form (``opt.pass_aborts``).  Correctness never rests on a pass being
right; it rests on the sandwich.

Entry points: :func:`optimize_program` (what ``Executor.run`` calls
once per ``(program, version, fetches)`` under ``PADDLE_TPU_OPT=1``,
and ``paddle_tpu opt`` wraps for offline inspection),
:class:`PassPipeline` (compose your own), and the individual passes in
:mod:`~paddle_tpu.analysis.opt.passes`.
"""

from paddle_tpu.analysis.opt.pipeline import (DEFAULT_PASSES, OptReport,
                                              PassPipeline,
                                              optimize_program)
from paddle_tpu.analysis.opt import passes

__all__ = ["PassPipeline", "OptReport", "optimize_program",
           "DEFAULT_PASSES", "passes"]
