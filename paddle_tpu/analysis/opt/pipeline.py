"""Pass pipeline with per-pass verify-sandwich.

The sandwich contract: the full analyzer (structure + types + lints)
runs over the program BEFORE the pipeline and AFTER every pass.  The
diagnostic set may only shrink — a pass that *introduces* any
``(code, var, op_type)`` finding not present before it ran is aborted:
its output is discarded, the program reverts to the pre-pass form, and
the abort lands in the report (``opt.pass_aborts``) instead of in a
user's step.  Passes therefore never need to be trusted, only checked.

Each pass mutates a clone; the input program is never touched.  The
pipeline's output carries the per-pass stats (:class:`OptReport`) plus
two statically proven fact attachments:

* ``program._opt_rng_plan = True`` — every op was classified through
  the shared op-metadata registry (``analysis/opmeta.py``); ops that
  provably never consume an RNG key are marked so ``lower_block``
  skips their per-op ``jax.random.fold_in`` (a traced threefry
  computation each) without perturbing the keys RNG ops receive —
  removed/fused ops leave ``__rng_slots__`` attrs behind so surviving
  RNG consumers keep their exact pre-optimization key positions;
* ``program._donation_plan`` — the donation/aliasing planner's facts
  (``memory_optimization_transpiler.plan_donation``), proven safe by
  the PTA009 donation-hazard lint.
"""

from __future__ import annotations

import logging

from paddle_tpu import framework
from paddle_tpu.framework import Program

logger = logging.getLogger(__name__)

__all__ = ["PassPipeline", "OptReport", "optimize_program",
           "DEFAULT_PASSES", "clone_program"]

#: the default pass order: fold first (turns arithmetic into
#: constants), CSE second (folding exposes duplicates), DCE third
#: (removes what folding/CSE orphaned plus unfetched autodiff chains),
#: fusion over the final op list, then the two fact emitters — the
#: donation planner and the cost-model compile-amortization gate
DEFAULT_PASSES = ("constant_fold", "cse", "dce", "fuse_elementwise",
                  "donation_plan", "amortize")

#: program attributes the executor/serving layers key behavior off
#: that ``Program.to_dict`` does not carry — the optimized clone must
#: behave identically in every respect but its op list
_RUNTIME_ATTRS = ("_is_inference", "lod_buckets", "check_nan_inf",
                  "_mfu_gauge", "expect_host_ops",
                  # facts earlier passes attached (clone-per-pass must
                  # not drop them)
                  "_donation_plan", "_opt_interpret")


def clone_program(program):
    """Deep-copy ``program`` including the runtime attributes the
    serialization round-trip drops."""
    p = Program.from_dict(program.to_dict())
    program._copy_param_attrs_to(p)
    for attr in _RUNTIME_ATTRS:
        if hasattr(program, attr):
            setattr(p, attr, getattr(program, attr))
    return p


def _diag_keys(result):
    """The sandwich's invariant set: op indices shift as passes remove
    ops, so findings are keyed structurally."""
    return {(d.code, d.var, d.op_type) for d in result.diagnostics}


class OptReport:
    """What the pipeline did: one entry per pass plus program-level
    before/after counts (the ``paddle_tpu opt`` diff report)."""

    def __init__(self):
        self.passes = []          # per-pass dicts
        self.ops_before = 0
        self.ops_after = 0
        self.flops_before = None
        self.flops_after = None

    def add(self, name, status, ops_before, ops_after, stats=None,
            new_diagnostics=()):
        self.passes.append({
            "pass": name, "status": status,
            "ops_before": ops_before, "ops_after": ops_after,
            "stats": dict(stats or {}),
            "new_diagnostics": [d.to_dict() for d in new_diagnostics],
        })

    @property
    def aborted_passes(self):
        return [p["pass"] for p in self.passes
                if p["status"] == "aborted"]

    def ops_removed(self):
        return max(self.ops_before - self.ops_after, 0)

    def to_dict(self):
        return {"format": 1, "ops_before": self.ops_before,
                "ops_after": self.ops_after,
                "flops_before": self.flops_before,
                "flops_after": self.flops_after,
                "passes": self.passes}

    def format(self):
        lines = [f"optimization report: {self.ops_before} -> "
                 f"{self.ops_after} ops"]
        for p in self.passes:
            delta = p["ops_before"] - p["ops_after"]
            stats = ", ".join(f"{k}={v}" for k, v in
                              sorted(p["stats"].items()))
            line = (f"  {p['pass']:<18} {p['status']:<8} "
                    f"ops {p['ops_before']:>4} -> {p['ops_after']:<4}"
                    f" (-{delta})")
            if stats:
                line += f"  [{stats}]"
            lines.append(line)
            for d in p["new_diagnostics"]:
                lines.append(f"      rejected by sandwich: "
                             f"{d['severity']}[{d['code']}] {d['message']}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"OptReport(ops {self.ops_before}->{self.ops_after}, "
                f"passes={[(p['pass'], p['status']) for p in self.passes]})")


class PassPipeline:
    """Ordered passes, each verify-sandwiched.

    ``passes``: iterable of names from
    :data:`~paddle_tpu.analysis.opt.passes.PASS_REGISTRY` or callables
    ``fn(program, ctx) -> stats-dict`` (mutating ``program`` in
    place).  Callables are how the negative tests inject deliberately
    broken passes to prove the sandwich rejects them."""

    def __init__(self, passes=None):
        from paddle_tpu.analysis.opt.passes import PASS_REGISTRY
        selected = DEFAULT_PASSES if passes is None else passes
        self.passes = []
        for p in selected:
            if callable(p):
                self.passes.append((getattr(p, "__name__", "custom"), p))
            else:
                if p not in PASS_REGISTRY:
                    raise ValueError(
                        f"unknown optimization pass {p!r}; known: "
                        f"{sorted(PASS_REGISTRY)}")
                self.passes.append((p, PASS_REGISTRY[p]))

    def run(self, program, feed_names=None, fetch_names=None):
        """Optimize a clone of ``program``; returns ``(optimized,
        OptReport)``.  The input program is never mutated."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.analysis import analyzer
        from paddle_tpu.analysis.opt.passes import PassContext

        feed_names = tuple(feed_names or ())
        fetch_names = tuple(fetch_names or ())
        report = OptReport()
        current = clone_program(program)
        report.ops_before = _op_count(current)

        baseline = analyzer.analyze_program(
            current, feed_names=feed_names, fetch_names=fetch_names)
        invariant = _diag_keys(baseline)
        ctx = PassContext(feed_names=feed_names, fetch_names=fetch_names)

        for name, fn in self.passes:
            candidate = clone_program(current)
            ops_before = _op_count(candidate)
            try:
                stats = fn(candidate, ctx) or {}
            except Exception:
                logger.warning("optimization pass %r raised; skipped",
                               name, exc_info=True)
                _profiler.runtime_metrics.inc("opt.pass_aborts")
                report.add(name, "aborted", ops_before, ops_before,
                           {"raised": 1})
                continue
            after = analyzer.analyze_program(
                candidate, feed_names=feed_names,
                fetch_names=fetch_names)
            introduced = [d for d in after.diagnostics
                          if (d.code, d.var, d.op_type) not in invariant]
            if introduced:
                # the sandwich: ANY new finding rejects the pass
                _profiler.runtime_metrics.inc("opt.pass_aborts")
                report.add(name, "aborted", ops_before, ops_before,
                           stats, new_diagnostics=introduced)
                logger.warning(
                    "optimization pass %r introduced %d diagnostic(s); "
                    "reverted to the pre-pass program", name,
                    len(introduced))
                continue
            status = "applied" if (stats or
                                   _op_count(candidate) != ops_before) \
                else "noop"
            report.add(name, status, ops_before, _op_count(candidate),
                       stats)
            current = candidate
            invariant = _diag_keys(after)

        report.ops_after = _op_count(current)
        _profiler.runtime_metrics.inc("opt.programs")
        _profiler.runtime_metrics.inc("opt.ops_removed",
                                      report.ops_removed())
        # statically proven trace facts: every op classified through
        # the shared op-metadata registry — lower_block may skip the
        # per-op fold_in for ops that provably never consume a key
        current._opt_rng_plan = True
        current._opt_report = report
        return current, report


def _op_count(program):
    return sum(len(b.ops) for b in program.blocks)


def optimize_program(program, feed_names=None, fetch_names=None,
                     passes=None):
    """Run the (default) pipeline over ``program``; returns
    ``(optimized_program, OptReport)``.  This is the entry
    ``Executor.run`` memoizes per ``(program, version, fetches)`` under
    ``PADDLE_TPU_OPT=1`` and ``paddle_tpu opt`` exposes offline."""
    from paddle_tpu import profiler as _profiler
    with _profiler.record_latency("opt.seconds"):
        pipe = PassPipeline(passes)
        optimized, report = pipe.run(program, feed_names=feed_names,
                                     fetch_names=fetch_names)
    try:
        from paddle_tpu.analysis import cost
        report.flops_before = cost.estimate(program).total_flops
        report.flops_after = cost.estimate(optimized).total_flops
    except Exception:  # the report survives a cost-model gap
        logger.debug("cost estimate for the opt report failed",
                     exc_info=True)
    return optimized, report
