"""Diagnostic model for the Program IR static analyzer.

Every finding the analyzer can emit has a STABLE code (``PTA001``...),
a severity, and a one-line title.  The code is the contract: docs list
every code in ``docs/static_analysis.md``, the registry test
(``tests/test_analysis_registry.py``) enforces that each code is both
documented and covered by a negative test, and CI greps for codes — so
codes are never renumbered or reused.

Severities:
  * ``error``   — the program is provably ill-formed; ``verify_program``
    raises, ``paddle_tpu lint`` exits non-zero.
  * ``warning`` — the program will run but almost certainly not the way
    its author intended (dead ops, unused feeds, donation hazards).

The analyzer's contract is ZERO false positives: a check only fires on
facts provable from the IR alone (all participating shapes/dtypes
statically known, every alias accounted for).  Anything uncertain is
silent — uncovered op types land on the warn-list
(``AnalysisResult.uncovered_op_types``) instead of guessing.
"""

from __future__ import annotations

__all__ = ["DIAGNOSTIC_CODES", "Diagnostic", "ProgramVerificationError",
           "format_diagnostics"]

#: code -> (severity, one-line title).  Append-only; see module docstring.
DIAGNOSTIC_CODES = {
    "PTA001": ("error", "use of undefined variable"),
    "PTA002": ("error", "variable read before it is written"),
    "PTA003": ("error", "missing feed/fetch target"),
    "PTA004": ("error", "persistable variable re-defined inside a step"),
    "PTA005": ("error", "dtype mismatch"),
    "PTA006": ("error", "shape mismatch"),
    "PTA007": ("warning", "dead op (outputs never consumed nor fetched)"),
    "PTA008": ("warning", "unused feed"),
    "PTA009": ("warning", "donated buffer read after its donating op"),
    "PTA010": ("error", "int64 value will silently truncate to int32"),
    # distributed verifier (analysis/distributed.py): cross-program
    # checks over the families a transpile produces — SPMD replicas,
    # pipeline stage sets, trainer/pserver pairs, gen bundles
    "PTA011": ("error",
               "collectives desynced across distributed programs "
               "(static deadlock)"),
    "PTA012": ("error",
               "matched collectives disagree on axis/participants/"
               "shape/dtype"),
    "PTA013": ("error", "Send without matching Recv (or vice versa) "
                        "in a transpiled pair"),
    "PTA014": ("error",
               "parameter/gradient split blocks do not reassemble to "
               "the original shape"),
    "PTA015": ("error",
               "pipeline stage boundary carrier mismatch between "
               "producer and consumer"),
    "PTA016": ("error", "invalid or conflicting sharding spec"),
    "PTA017": ("warning",
               "implicit full reshard (operands sharded differently)"),
    "PTA018": ("warning",
               "recompile hazard: feed can escape its declared "
               "row-bucket edges"),
    "PTA019": ("error",
               "gen bundle prefill/decode signature drift"),
}


class Diagnostic:
    """One analyzer finding, formatted rustc-style by :meth:`format`."""

    __slots__ = ("code", "severity", "message", "block_idx", "op_index",
                 "op_type", "var", "site", "program")

    def __init__(self, code, message, block_idx=None, op_index=None,
                 op_type=None, var=None, site=None, program=None):
        if code not in DIAGNOSTIC_CODES:
            raise ValueError(f"unknown diagnostic code {code!r}")
        self.code = code
        self.severity = DIAGNOSTIC_CODES[code][0]
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.site = site  # (filename, lineno) construction site or None
        self.program = program  # member label in a multi-program lint

    @property
    def title(self):
        return DIAGNOSTIC_CODES[self.code][1]

    def location(self):
        parts = []
        if self.program is not None:
            parts.append(f"program `{self.program}`")
        if self.block_idx is not None:
            parts.append(f"block {self.block_idx}")
        if self.op_index is not None:
            parts.append(f"op #{self.op_index}"
                         + (f" `{self.op_type}`" if self.op_type else ""))
        elif self.op_type:
            parts.append(f"op `{self.op_type}`")
        if self.var:
            parts.append(f"var `{self.var}`")
        return ", ".join(parts)

    def format(self):
        lines = [f"{self.severity}[{self.code}]: {self.message}"]
        loc = self.location()
        if loc:
            lines.append(f"  --> {loc}")
        if self.site:
            lines.append(f"   = constructed at {self.site[0]}:{self.site[1]}")
        return "\n".join(lines)

    def to_dict(self):
        return {"code": self.code, "severity": self.severity,
                "message": self.message, "block": self.block_idx,
                "op_index": self.op_index, "op_type": self.op_type,
                "var": self.var, "program": self.program,
                "site": list(self.site) if self.site else None}

    def __repr__(self):
        return f"Diagnostic({self.code}, {self.message!r})"

    __str__ = format


def format_diagnostics(diags):
    """Render a diagnostic list the way ``paddle_tpu lint`` prints it."""
    return "\n".join(d.format() for d in diags)


class ProgramVerificationError(Exception):
    """Raised when a verified program carries error-severity diagnostics.

    ``diagnostics`` holds every finding (warnings included); ``where``
    names the verification site (``executor.run``, ``append_backward``,
    a transpiler) so the traceback says WHICH rewrite emitted the
    ill-formed program."""

    def __init__(self, diagnostics, where="verify_program"):
        self.diagnostics = list(diagnostics)
        self.where = where
        errors = [d for d in self.diagnostics if d.severity == "error"]
        head = (f"{where}: program verification failed with "
                f"{len(errors)} error(s)")
        super().__init__(head + "\n" + format_diagnostics(self.diagnostics))
