"""Shape & dtype inference pass: dataflow over the Program IR.

Unlike the build-time ``registry.infer_shape`` hooks (best-effort hints
that mutate the Variables as layers are appended), this pass trusts
NOTHING it cannot prove.  It seeds a shadow environment from the
program's declared roots — ``is_data`` feeds, Parameters and other
persistables (whose shapes/dtypes the user or the initializer pinned) —
and propagates shapes/dtypes forward through per-op-type **rules**
registered with :func:`rule`.  An op type without a rule propagates
*unknown* for its outputs and lands on the warn-list
(``TypeEnv.uncovered``) instead of guessing; a rule only reports a
mismatch (PTA005/PTA006) when every participating dim/dtype is
statically known.  That is the zero-false-positive contract: silence is
allowed, wrong noise is not.

Registering a rule for a new op::

    from paddle_tpu.analysis import typecheck

    @typecheck.rule("my_op")
    def _my_op(op, tc):
        x = tc.info(op.input("X")[0])
        if x.dtype is not None and x.dtype not in ("float32", "bfloat16"):
            tc.report("PTA005", f"my_op needs a float X, got {x.dtype}",
                      op=op, var=op.input("X")[0])
        tc.set_output(op, "Out", shape=x.shape, dtype=x.dtype)

``-1``/``None`` dims mean *unknown* and match anything; ``dtype=None``
likewise.  PTA010 (int64 → i32 lane truncation) also lives here: the
``fill_constant``/``fill`` rules prove from the literal attr value that
a device-side int64 constant exceeds int32 range — under JAX's default
x64-off mode (and on the pipeline transpiler's typed i32 carrier lane)
such a value silently wraps.
"""

from __future__ import annotations

import logging

import numpy as np

from paddle_tpu import framework
from paddle_tpu.analysis.diagnostics import Diagnostic

logger = logging.getLogger(__name__)

__all__ = ["rule", "check_types", "TypeEnv", "VarInfo", "covered_op_types",
           "INT32_MAX", "INT32_MIN", "int64_fits_i32_lane"]

INT32_MAX = np.iinfo(np.int32).max
INT32_MIN = np.iinfo(np.int32).min

_RULES = {}

_INT_DTYPES = ("int8", "uint8", "int16", "int32", "int64", "bool")


def rule(*op_types):
    """Decorator registering ``fn(op, tc)`` as the inference rule for
    one or more op types (the analysis-side analog of
    ``registry.register_op``'s ``infer_shape``)."""

    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn

    return deco


def covered_op_types():
    return set(_RULES)


def int64_fits_i32_lane(values):
    """True when every value is exactly representable in int32 — the
    contract of the pipeline transpiler's i32 carrier lane and of JAX's
    x64-off int handling."""
    a = np.asarray(values)
    if a.size == 0:
        return True
    return bool(a.max() <= INT32_MAX and a.min() >= INT32_MIN)


class VarInfo:
    __slots__ = ("shape", "dtype")

    def __init__(self, shape=None, dtype=None):
        # normalize: unknown dims -> -1; unknown shape -> None
        self.shape = None if shape is None else tuple(
            -1 if d is None or int(d) < 0 else int(d) for d in shape)
        self.dtype = dtype

    def __repr__(self):
        return f"VarInfo(shape={self.shape}, dtype={self.dtype})"


_UNKNOWN = VarInfo()


class TypeEnv:
    """Shadow (shape, dtype) environment threaded through one block."""

    def __init__(self, block, diags, uncovered, op_index=None):
        self.block = block
        self.diags = diags
        self.uncovered = uncovered
        self.op_index = op_index
        self._env = {}

    # -- reads -------------------------------------------------------------
    def info(self, name):
        if not name:
            return _UNKNOWN
        if name in self._env:
            return self._env[name]
        # trusted roots: declared feeds and persistable state carry
        # user/initializer-pinned metadata; scratch vars do not (their
        # declared dtype is just the auto-declare default)
        try:
            v = self.block.var(name)
        except KeyError:
            return _UNKNOWN
        if getattr(v, "is_data", False) or getattr(v, "persistable", False):
            return VarInfo(v.shape, v.dtype)
        return _UNKNOWN

    def input_info(self, op, slot):
        names = op.input(slot)
        return self.info(names[0]) if names else _UNKNOWN

    # -- writes ------------------------------------------------------------
    def set(self, name, shape=None, dtype=None):
        if name:
            self._env[name] = VarInfo(shape, dtype)

    def set_output(self, op, slot, shape=None, dtype=None):
        for n in op.output(slot):
            self.set(n, shape=shape, dtype=dtype)

    def copy_unary(self, op, in_slot="X", out_slot="Out"):
        x = self.input_info(op, in_slot)
        self.set_output(op, out_slot, shape=x.shape, dtype=x.dtype)

    # -- reporting ---------------------------------------------------------
    def report(self, code, message, op=None, var=None):
        self.diags.append(Diagnostic(
            code, message, block_idx=self.block.idx,
            op_index=self.op_index,
            op_type=op.type if op is not None else None, var=var,
            site=getattr(op, "creation_site", None)))


def _dims_conflict(a, b):
    """Both known and different (the provable-mismatch predicate)."""
    return a != -1 and b != -1 and a != b


def check_types(program):
    """Run the inference pass over every block reachable from block 0.

    Returns ``(diagnostics, uncovered_op_types)`` where the second item
    is the warn-list: op types seen in the program that have no
    registered inference rule (their outputs propagated as unknown)."""
    diags = []
    uncovered = set()
    _check_block(program.global_block(), diags, uncovered, parent_env=None)
    return diags, uncovered


def _check_block(block, diags, uncovered, parent_env):
    tc = TypeEnv(block, diags, uncovered)
    if parent_env is not None:
        tc._env.update(parent_env)
    for i, op in enumerate(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        tc.op_index = i
        fn = _RULES.get(op.type)
        if fn is None:
            uncovered.add(op.type)
            for n in op.output_arg_names:
                tc.set(n)  # unknown stops propagation, never misreports
        else:
            try:
                fn(op, tc)
            except Exception:  # lint must never crash on the malformed
                # programs it exists to diagnose (e.g. an op that lost a
                # required input slot): degrade this op to no-rule
                # behavior — outputs unknown, op on the warn-list — and
                # let the structural pass name the actual defect
                logger.warning(
                    "analysis rule for op %r failed; treating the op as "
                    "uncovered", op.type, exc_info=True)
                uncovered.add(op.type)
                for n in op.output_arg_names:
                    tc.set(n)
        for a in op.attrs.values():
            if isinstance(a, framework.Block):
                _check_block(a, diags, uncovered, parent_env=tc._env)
    return tc


# ---------------------------------------------------------------------------
# core rules
# ---------------------------------------------------------------------------

_UNARY_OPS = (
    "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "abs", "square",
    "softmax", "softsign", "softplus", "relu6", "leaky_relu", "elu",
    "gelu", "hard_sigmoid", "swish", "brelu", "pow", "reciprocal",
    "floor", "ceil", "round", "sin", "cos", "clip", "scale", "assign",
    "dropout", "label_smooth", "sequence_softmax", "fill_zeros_like",
)


@rule(*_UNARY_OPS)
def _r_unary(op, tc):
    tc.copy_unary(op)


@rule("mul")
def _r_mul(op, tc):
    x = tc.input_info(op, "X")
    y = tc.input_info(op, "Y")
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    out_shape = None
    if x.dtype is not None and y.dtype is not None and x.dtype != y.dtype:
        tc.report("PTA005",
                  f"mul operands disagree on dtype: X `{op.input('X')[0]}` "
                  f"is {x.dtype}, Y `{op.input('Y')[0]}` is {y.dtype}",
                  op=op, var=op.input("X")[0])
    if x.shape is not None and y.shape is not None and \
            len(x.shape) >= xn and len(y.shape) >= yn:
        k_x = _prod(x.shape[xn:])
        k_y = _prod(y.shape[:yn])
        if k_x is not None and k_y is not None and k_x != k_y:
            tc.report("PTA006",
                      f"mul inner dimensions differ: X "
                      f"`{op.input('X')[0]}` {x.shape} flattens to "
                      f"[*, {k_x}] but Y `{op.input('Y')[0]}` {y.shape} "
                      f"flattens to [{k_y}, *]",
                      op=op, var=op.input("X")[0])
        out_shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    tc.set_output(op, "Out", shape=out_shape, dtype=x.dtype)


def _prod(dims):
    n = 1
    for d in dims:
        if d == -1:
            return None
        n *= d
    return n


@rule("matmul")
def _r_matmul(op, tc):
    x = tc.input_info(op, "X")
    y = tc.input_info(op, "Y")
    if x.dtype is not None and y.dtype is not None and x.dtype != y.dtype:
        tc.report("PTA005",
                  f"matmul operands disagree on dtype: {x.dtype} vs "
                  f"{y.dtype}", op=op, var=op.input("X")[0])
    out_shape = None
    if x.shape is not None and y.shape is not None and \
            len(x.shape) >= 2 and len(y.shape) >= 2:
        xs = list(x.shape)
        ys = list(y.shape)
        if op.attr("transpose_X", False):
            xs[-1], xs[-2] = xs[-2], xs[-1]
        if op.attr("transpose_Y", False):
            ys[-1], ys[-2] = ys[-2], ys[-1]
        if _dims_conflict(xs[-1], ys[-2]):
            tc.report("PTA006",
                      f"matmul contraction dims differ: X "
                      f"`{op.input('X')[0]}` {x.shape} contracts "
                      f"{xs[-1]} against Y `{op.input('Y')[0]}` "
                      f"{y.shape}'s {ys[-2]}",
                      op=op, var=op.input("X")[0])
        batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
        out_shape = tuple(batch) + (xs[-2], ys[-1])
    tc.set_output(op, "Out", shape=out_shape, dtype=x.dtype)


@rule("elementwise_add", "elementwise_sub", "elementwise_mul",
      "elementwise_div", "elementwise_max", "elementwise_min",
      "elementwise_pow")
def _r_elementwise(op, tc):
    x = tc.input_info(op, "X")
    y = tc.input_info(op, "Y")
    if x.dtype is not None and y.dtype is not None and x.dtype != y.dtype:
        tc.report("PTA005",
                  f"{op.type} operands disagree on dtype: X "
                  f"`{op.input('X')[0]}` is {x.dtype}, Y "
                  f"`{op.input('Y')[0]}` is {y.dtype} (insert a cast)",
                  op=op, var=op.input("Y")[0])
    if x.shape is not None and y.shape is not None:
        axis = op.attr("axis", -1)
        if axis == -1:
            axis = len(x.shape) - len(y.shape)
        ok = 0 <= axis and axis + len(y.shape) <= len(x.shape)
        if ok:
            for i, dy in enumerate(y.shape):
                dx = x.shape[axis + i]
                if dy != 1 and _dims_conflict(dx, dy):
                    ok = False
                    break
        if not ok:
            tc.report("PTA006",
                      f"{op.type}: Y `{op.input('Y')[0]}` {y.shape} does "
                      f"not broadcast into X `{op.input('X')[0]}` "
                      f"{x.shape} at axis {op.attr('axis', -1)}",
                      op=op, var=op.input("Y")[0])
    tc.set_output(op, "Out", shape=x.shape, dtype=x.dtype)


@rule("sum")
def _r_sum(op, tc):
    infos = [tc.info(n) for n in op.input("X")]
    shape = None
    dtype = None
    for n, inf in zip(op.input("X"), infos):
        if inf.dtype is not None:
            if dtype is not None and inf.dtype != dtype:
                tc.report("PTA005",
                          f"sum inputs disagree on dtype: `{n}` is "
                          f"{inf.dtype}, earlier inputs are {dtype}",
                          op=op, var=n)
            dtype = dtype or inf.dtype
        if inf.shape is not None:
            if shape is not None and len(shape) == len(inf.shape) and \
                    any(_dims_conflict(a, b)
                        for a, b in zip(shape, inf.shape)):
                tc.report("PTA006",
                          f"sum inputs disagree on shape: `{n}` is "
                          f"{inf.shape}, earlier inputs are {shape}",
                          op=op, var=n)
            shape = shape or inf.shape
    tc.set_output(op, "Out", shape=shape, dtype=dtype)


@rule("cast")
def _r_cast(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Out", shape=x.shape,
                  dtype=op.attr("out_dtype", op.attr("dtype")))


@rule("mean")
def _r_mean(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Out", shape=(1,), dtype=x.dtype)


@rule("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
      "reduce_prod")
def _r_reduce(op, tc):
    x = tc.input_info(op, "X")
    shape = None
    if x.shape is not None:
        dims = op.attr("dim")
        keep = op.attr("keep_dim", False)
        if op.attr("reduce_all", False) or dims is None:
            shape = (1,) * len(x.shape) if keep else (1,)
        else:
            dims = [d % len(x.shape) for d in
                    (dims if isinstance(dims, (list, tuple)) else [dims])]
            shape = tuple(1 if i in dims else d
                          for i, d in enumerate(x.shape)) if keep else \
                tuple(d for i, d in enumerate(x.shape) if i not in dims) \
                or (1,)
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("cross_entropy")
def _r_cross_entropy(op, tc):
    x = tc.input_info(op, "X")
    label = tc.input_info(op, "Label")
    if not op.attr("soft_label", False) and label.dtype is not None and \
            label.dtype not in ("int32", "int64"):
        tc.report("PTA005",
                  f"cross_entropy with hard labels needs an integer "
                  f"Label, got {label.dtype} for "
                  f"`{op.input('Label')[0]}`",
                  op=op, var=op.input("Label")[0])
    if x.shape is not None and label.shape is not None and \
            len(x.shape) == len(label.shape) and \
            _dims_conflict(x.shape[0], label.shape[0]):
        tc.report("PTA006",
                  f"cross_entropy batch dims differ: X {x.shape} vs "
                  f"Label {label.shape}", op=op, var=op.input("X")[0])
    shape = None
    if x.shape is not None:
        shape = tuple(x.shape[:-1]) + (1,)
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("softmax_with_cross_entropy")
def _r_softmax_xent(op, tc):
    x = tc.input_info(op, "Logits")
    tc.set_output(op, "Softmax", shape=x.shape, dtype=x.dtype)
    shape = tuple(x.shape[:-1]) + (1,) if x.shape is not None else None
    tc.set_output(op, "Loss", shape=shape, dtype=x.dtype)


@rule("accuracy")
def _r_accuracy(op, tc):
    out = tc.input_info(op, "Out")
    label = tc.input_info(op, "Label")
    if label.dtype is not None and label.dtype not in ("int32", "int64"):
        tc.report("PTA005",
                  f"accuracy needs an integer Label, got {label.dtype}",
                  op=op, var=op.input("Label")[0])
    if out.shape is not None and label.shape is not None and \
            _dims_conflict(out.shape[0], label.shape[0]):
        tc.report("PTA006",
                  f"accuracy batch dims differ: Out {out.shape} vs "
                  f"Label {label.shape}", op=op, var=op.input("Out")[0])
    tc.set_output(op, "Accuracy", shape=(1,), dtype="float32")
    tc.set_output(op, "Correct", shape=(1,), dtype="int64")
    tc.set_output(op, "Total", shape=(1,), dtype="int64")


@rule("top_k")
def _r_top_k(op, tc):
    x = tc.input_info(op, "X")
    k = op.attr("k", 1)
    shape = tuple(x.shape[:-1]) + (k,) if x.shape is not None else None
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)
    tc.set_output(op, "Indices", shape=shape, dtype="int64")


@rule("lookup_table")
def _r_lookup_table(op, tc):
    ids = tc.input_info(op, "Ids")
    w = tc.input_info(op, "W")
    if ids.dtype is not None and ids.dtype not in ("int32", "int64"):
        tc.report("PTA005",
                  f"lookup_table Ids `{op.input('Ids')[0]}` must be "
                  f"integer, got {ids.dtype}",
                  op=op, var=op.input("Ids")[0])
    shape = None
    if ids.shape is not None and w.shape is not None and \
            len(w.shape) == 2:
        lead = ids.shape[:-1] if ids.shape and ids.shape[-1] == 1 \
            else ids.shape
        shape = tuple(lead) + (w.shape[1],)
    tc.set_output(op, "Out", shape=shape, dtype=w.dtype)


# -- sparse / CTR family (ops/sparse_ops.py) --------------------------------
#
# SelectedRows values flow through ordinary variables; their static
# type is the LOGICAL dense shape ([height, dim]) — the same convention
# ``lookup_table_grad``'s mirror rule applies to its SelectedRows
# cotangent (W@GRAD gets W's [vocab, dim] shape regardless of how many
# rows the batch touched), so the optimizer Param/Grad agreement check
# sees through the sparse path unchanged.

@rule("merge_selected_rows", "get_tensor_from_selected_rows")
def _r_selected_rows_unary(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Out", shape=x.shape, dtype=x.dtype)


@rule("split_ids")
def _r_split_ids(op, tc):
    ids = tc.input_info(op, "Ids")
    if ids.dtype is not None and ids.dtype not in ("int32", "int64"):
        tc.report("PTA005",
                  f"split_ids Ids `{op.input('Ids')[0]}` must be "
                  f"integer, got {ids.dtype}",
                  op=op, var=op.input("Ids")[0])
    n = None
    if ids.shape is not None:
        n = 1
        for d in ids.shape:
            if d is None or d < 0:
                n = -1
                break
            n *= int(d)
    for name in op.output("Out"):
        tc.set(name, shape=None if n is None else (n, 1),
               dtype=ids.dtype)


@rule("split_selected_rows")
def _r_split_selected_rows(op, tc):
    x = tc.input_info(op, "X")
    sections = op.attr("height_sections", []) or []
    names = op.output("Out")
    for i, name in enumerate(names):
        shape = None
        if x.shape is not None and len(x.shape) >= 2 and \
                i < len(sections):
            shape = (int(sections[i]),) + tuple(x.shape[1:])
        tc.set(name, shape=shape, dtype=x.dtype)


@rule("nce")
def _r_nce(op, tc):
    x = tc.input_info(op, "Input")
    label = tc.input_info(op, "Label")
    if label.dtype is not None and label.dtype not in ("int32", "int64"):
        tc.report("PTA005",
                  f"nce Label `{op.input('Label')[0]}` must be "
                  f"integer, got {label.dtype}",
                  op=op, var=op.input("Label")[0])
    n = x.shape[0] if x.shape is not None else None
    num_true = (label.shape[1] if label.shape is not None and
                len(label.shape) == 2 else 1)
    num_sampled = num_true + int(op.attr("num_neg_samples", 10))
    tc.set_output(op, "Cost", shape=None if n is None else (n, 1),
                  dtype=x.dtype)
    for slot, dt in (("SampleLogits", x.dtype),
                     ("SampleLabels", "int64")):
        if op.output(slot):
            tc.set(op.output(slot)[0],
                   shape=None if n is None else (n, num_sampled),
                   dtype=dt)


@rule("fill_constant", "fill")
def _r_fill_constant(op, tc):
    dtype = op.attr("dtype", "float32")
    shape = op.attr("shape")
    value = op.attr("value", 0.0)
    if dtype in ("int64",) and value is not None:
        try:
            fits = int64_fits_i32_lane(value)
        except (TypeError, ValueError):
            fits = True
        if not fits:
            name = op.output("Out")[0] if op.output("Out") else None
            tc.report("PTA010",
                      f"{op.type} writes int64 value(s) outside int32 "
                      f"range into `{name}` — under JAX x64-off (and on "
                      f"the pipeline i32 carrier lane) the value "
                      f"silently wraps; keep ids within int32 range or "
                      f"stage them host-side",
                      op=op, var=name)
    tc.set_output(op, "Out", shape=shape, dtype=dtype)


@rule("uniform_random", "gaussian_random")
def _r_random_init(op, tc):
    tc.set_output(op, "Out", shape=op.attr("shape"),
                  dtype=op.attr("dtype", "float32"))


@rule("fill_constant_batch_size_like")
def _r_fill_batch_like(op, tc):
    x = tc.input_info(op, "Input")
    shape = list(op.attr("shape") or ())
    if shape:
        out_idx = op.attr("output_dim_idx", 0)
        in_idx = op.attr("input_dim_idx", 0)
        if x.shape is not None and in_idx < len(x.shape) and \
                out_idx < len(shape):
            shape[out_idx] = x.shape[in_idx]
    tc.set_output(op, "Out", shape=shape or None,
                  dtype=op.attr("dtype", "float32"))


@rule("reshape", "reshape2")
def _r_reshape(op, tc):
    x = tc.input_info(op, "X")
    shape = list(op.attr("shape") or ())
    if shape and x.shape is not None:
        n_in = _prod(x.shape)
        unknown = sum(1 for d in shape if d in (-1, 0))
        if n_in is not None and unknown == 0:
            n_out = _prod(shape)
            if n_out is not None and n_out != n_in:
                tc.report("PTA006",
                          f"reshape of `{op.input('X')[0]}` {x.shape} "
                          f"({n_in} elements) to {tuple(shape)} "
                          f"({n_out} elements) changes the element "
                          f"count", op=op, var=op.input("X")[0])
    tc.set_output(op, "Out", shape=shape or None, dtype=x.dtype)


@rule("transpose", "transpose2")
def _r_transpose(op, tc):
    x = tc.input_info(op, "X")
    perm = op.attr("axis") or op.attr("perm")
    shape = None
    if x.shape is not None and perm and len(perm) == len(x.shape):
        shape = tuple(x.shape[p] for p in perm)
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("concat")
def _r_concat(op, tc):
    infos = [tc.info(n) for n in op.input("X")]
    axis = op.attr("axis", 0)
    shape = None
    dtype = None
    known = [i for i in infos if i.shape is not None]
    for n, inf in zip(op.input("X"), infos):
        if inf.dtype is not None:
            if dtype is not None and inf.dtype != dtype:
                tc.report("PTA005",
                          f"concat inputs disagree on dtype: `{n}` is "
                          f"{inf.dtype}, earlier inputs are {dtype}",
                          op=op, var=n)
            dtype = dtype or inf.dtype
    if known and all(len(i.shape) == len(known[0].shape) for i in known):
        rank = len(known[0].shape)
        ax = axis % rank if rank else 0
        for d in range(rank):
            if d == ax:
                continue
            dims = {i.shape[d] for i in known if i.shape[d] != -1}
            if len(dims) > 1:
                tc.report("PTA006",
                          f"concat inputs disagree on non-concat dim "
                          f"{d}: {sorted(dims)}", op=op,
                          var=op.input("X")[0])
                break
        if len(known) == len(infos):
            cat = 0
            for i in known:
                if i.shape[ax] == -1:
                    cat = -1
                    break
                cat += i.shape[ax]
            shape = tuple(cat if d == ax else known[0].shape[d]
                          for d in range(rank))
    tc.set_output(op, "Out", shape=shape, dtype=dtype)


@rule("conv2d")
def _r_conv2d(op, tc):
    x = tc.input_info(op, "Input")
    w = tc.input_info(op, "Filter")
    shape = None
    if x.shape is not None and w.shape is not None and \
            len(x.shape) == 4 and len(w.shape) == 4:
        if _dims_conflict(x.shape[1],
                          w.shape[1] * op.attr("groups", 1)):
            tc.report("PTA006",
                      f"conv2d channel mismatch: Input "
                      f"`{op.input('Input')[0]}` has {x.shape[1]} "
                      f"channels but Filter `{op.input('Filter')[0]}` "
                      f"expects {w.shape[1] * op.attr('groups', 1)}",
                      op=op, var=op.input("Input")[0])
        stride = _pair(op.attr("strides", [1, 1]))
        pad = _pair(op.attr("paddings", [0, 0]))
        dil = _pair(op.attr("dilations", [1, 1]))
        hw = []
        for i in (0, 1):
            d_in = x.shape[2 + i]
            if d_in == -1 or w.shape[2 + i] == -1:
                hw.append(-1)
            else:
                k = dil[i] * (w.shape[2 + i] - 1) + 1
                hw.append((d_in + 2 * pad[i] - k) // stride[i] + 1)
        shape = (x.shape[0], w.shape[0], hw[0], hw[1])
    tc.set_output(op, "Output", shape=shape, dtype=x.dtype)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


@rule("pool2d")
def _r_pool2d(op, tc):
    x = tc.input_info(op, "X")
    shape = None
    if x.shape is not None and len(x.shape) == 4:
        if op.attr("global_pooling", False):
            shape = (x.shape[0], x.shape[1], 1, 1)
        else:
            k = _pair(op.attr("ksize", [1, 1]))
            stride = _pair(op.attr("strides", [1, 1]))
            pad = _pair(op.attr("paddings", [0, 0]))
            ceil = op.attr("ceil_mode", False)
            hw = []
            for i in (0, 1):
                d_in = x.shape[2 + i]
                if d_in == -1:
                    hw.append(-1)
                    continue
                num = d_in + 2 * pad[i] - k[i]
                hw.append((num + stride[i] - 1) // stride[i] + 1 if ceil
                          else num // stride[i] + 1)
            shape = (x.shape[0], x.shape[1], hw[0], hw[1])
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("batch_norm")
def _r_batch_norm(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Y", shape=x.shape, dtype=x.dtype)


@rule("layer_norm")
def _r_layer_norm(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Y", shape=x.shape, dtype=x.dtype)


# ---------------------------------------------------------------------------
# gradient-op rules: the single largest warn-list family.  Every
# ``<type>_grad`` op built by ``registry.default_grad_maker`` follows
# one slot convention — inputs carry the forward slots (same names) and
# outputs carry ``<slot>@GRAD`` per differentiable forward input — and
# the cotangent of a tensor always has THAT TENSOR's shape and dtype.
# So one mirror rule covers the family soundly: each ``<slot>@GRAD``
# output copies the shape/dtype of the forward input it differentiates,
# index-aligned within the slot (nothing is ever *reported* here —
# propagation only, so downstream rules like the optimizer Param/Grad
# agreement can see through backward chains).
# ---------------------------------------------------------------------------

_GRAD_MIRROR_OPS = tuple(
    t + "_grad" for t in _UNARY_OPS + (
        "mul", "matmul", "elementwise_add", "elementwise_sub",
        "elementwise_mul", "elementwise_div", "elementwise_max",
        "elementwise_min", "elementwise_pow", "sum", "mean", "concat",
        "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
        "reduce_prod", "cross_entropy", "softmax_with_cross_entropy",
        "lookup_table", "nce", "reshape", "reshape2", "transpose",
        "transpose2", "conv2d", "pool2d", "batch_norm", "layer_norm",
        "sequence_pool", "lstm", "write_to_array", "read_from_array",
        "array_to_lod_tensor", "lod_tensor_to_array",
        "reorder_lod_tensor_by_rank",
    ))


@rule(*_GRAD_MIRROR_OPS)
def _r_grad_mirror(op, tc):
    for slot, names in op.outputs.items():
        if not slot.endswith(framework.GRAD_SUFFIX):
            # auxiliary outputs (saved state, scratch): unknown
            tc.set_output(op, slot)
            continue
        fwd = op.input(slot[:-len(framework.GRAD_SUFFIX)])
        for i, n in enumerate(names):
            src = tc.info(fwd[i]) if i < len(fwd) else _UNKNOWN
            tc.set(n, shape=src.shape, dtype=src.dtype)


@rule("increment")
def _r_increment(op, tc):
    tc.copy_unary(op)


@rule("assign_value")
def _r_assign_value(op, tc):
    tc.set_output(op, "Out", shape=op.attr("shape"),
                  dtype=op.attr("dtype", "float32"))


@rule("max_sequence_len")
def _r_max_sequence_len(op, tc):
    tc.set_output(op, "Out", shape=(1,), dtype="int64")


@rule("sequence_expand")
def _r_sequence_expand(op, tc):
    # row count follows the LoD expansion (unknown statically);
    # feature dims and dtype carry through
    x = tc.input_info(op, "X")
    shape = (-1,) + tuple(x.shape[1:]) if x.shape is not None else None
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("less_than", "less_equal", "greater_than", "greater_equal",
      "equal", "not_equal")
def _r_compare(op, tc):
    x = tc.input_info(op, "X")
    tc.set_output(op, "Out", shape=x.shape, dtype="bool")


@rule("sequence_pool")
def _r_sequence_pool(op, tc):
    # rows collapse per sequence: the batch dim is LoD-dependent
    # (unknown statically), the feature dims and dtype carry through
    x = tc.input_info(op, "X")
    shape = (-1,) + tuple(x.shape[1:]) if x.shape is not None else None
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)
    tc.set_output(op, "MaxIndex", shape=shape, dtype="int32")


# ---------------------------------------------------------------------------
# LoD/array plumbing + recurrent ops: coverage the cost model rides
# (shape inference is the prerequisite for bytes costing).  Row counts
# are LoD-dependent (unknown statically, -1); trailing feature dims and
# dtypes carry through exactly — propagation only, nothing reported.
# ---------------------------------------------------------------------------

@rule("write_to_array", "read_from_array", "array_to_lod_tensor",
      "lod_tensor_to_array", "reorder_lod_tensor_by_rank")
def _r_lod_array_plumbing(op, tc):
    x = tc.input_info(op, "X")
    shape = (-1,) + tuple(x.shape[1:]) if x.shape is not None else None
    tc.set_output(op, "Out", shape=shape, dtype=x.dtype)


@rule("lod_rank_table")
def _r_lod_rank_table(op, tc):
    # produces a rank-table object, not a tensor: nothing to propagate,
    # but the op is KNOWN (off the warn-list) — consumers' rules treat
    # the table input as unknown by construction
    tc.set_output(op, "Out")


@rule("lstm")
def _r_lstm(op, tc):
    x = tc.input_info(op, "Input")
    w = tc.input_info(op, "Weight")
    hidden = None
    if w.shape is not None and len(w.shape) == 2 and w.shape[0] != -1:
        hidden = w.shape[0]
    rows = x.shape[0] if x.shape is not None else -1
    shape = (rows, hidden) if hidden is not None else None
    tc.set_output(op, "Hidden", shape=shape, dtype=x.dtype)
    tc.set_output(op, "Cell", shape=shape, dtype=x.dtype)
    tc.set_output(op, "BatchGate")
    tc.set_output(op, "BatchCellPreAct")


@rule("sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
      "decayed_adagrad", "rmsprop", "ftrl", "lars_momentum")
def _r_optimizer(op, tc):
    p = tc.input_info(op, "Param")
    g = tc.input_info(op, "Grad")
    if p.shape is not None and g.shape is not None and \
            (len(p.shape) != len(g.shape) or
             any(_dims_conflict(a, b) for a, b in zip(p.shape, g.shape))):
        tc.report("PTA006",
                  f"{op.type}: Param `{op.input('Param')[0]}` {p.shape} "
                  f"and Grad `{op.input('Grad')[0]}` {g.shape} differ "
                  f"in shape", op=op, var=op.input("Param")[0])
    if p.dtype is not None and g.dtype is not None and p.dtype != g.dtype:
        tc.report("PTA005",
                  f"{op.type}: Param dtype {p.dtype} differs from Grad "
                  f"dtype {g.dtype}", op=op, var=op.input("Param")[0])
    tc.set_output(op, "ParamOut", shape=p.shape, dtype=p.dtype)


@rule("paged_attention")
def _r_paged_attention(op, tc):
    q = tc.input_info(op, "Q")
    kc = tc.input_info(op, "KCache")
    vc = tc.input_info(op, "VCache")
    for slot in ("PageTable", "Lens"):
        inf = tc.input_info(op, slot)
        if inf.dtype is not None and inf.dtype not in ("int32", "int64"):
            tc.report("PTA005",
                      f"paged_attention {slot} "
                      f"`{op.input(slot)[0]}` must be an integer index "
                      f"tensor, got {inf.dtype}",
                      op=op, var=op.input(slot)[0])
    if kc.shape is not None and vc.shape is not None and \
            (len(kc.shape) != len(vc.shape) or
             any(_dims_conflict(a, b)
                 for a, b in zip(kc.shape, vc.shape))):
        tc.report("PTA006",
                  f"paged_attention K/V pools disagree on geometry: "
                  f"KCache `{op.input('KCache')[0]}` {kc.shape} vs "
                  f"VCache `{op.input('VCache')[0]}` {vc.shape}",
                  op=op, var=op.input("KCache")[0])
    if q.shape is not None and kc.shape is not None and \
            q.shape[-1] > 0 and kc.shape[-1] > 0 and \
            q.shape[-1] != kc.shape[-1]:
        tc.report("PTA006",
                  f"paged_attention Q `{op.input('Q')[0]}` feature dim "
                  f"{q.shape[-1]} differs from the page pool's "
                  f"{kc.shape[-1]} — the scatter would write misshapen "
                  f"rows", op=op, var=op.input("Q")[0])
    n_head = op.attr("n_head", None)
    if n_head and q.shape is not None and q.shape[-1] > 0 and \
            q.shape[-1] % int(n_head):
        tc.report("PTA006",
                  f"paged_attention feature dim {q.shape[-1]} is not "
                  f"divisible by n_head={n_head}",
                  op=op, var=op.input("Q")[0])
    tc.set_output(op, "Out", shape=q.shape, dtype=q.dtype)
    tc.set_output(op, "KCacheOut", shape=kc.shape, dtype=kc.dtype)
    tc.set_output(op, "VCacheOut", shape=vc.shape, dtype=vc.dtype)
