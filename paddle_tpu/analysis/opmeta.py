"""Shared op-metadata registry: ONE place that classifies op types as
pure / effectful / stateful / host / sub-block-carrying.

Three consumers previously each needed this classification — the
dead-op lint's exemptions (``lints.py``), the optimization passes
(``analysis/opt``: DCE may only remove what is provably effect-free,
CSE may only merge what is provably pure, fusion may only collapse what
is provably elementwise-pure), and the static cost model
(``analysis/cost.py``: effectful/host ops cost host time, not FLOPs).
If those classifications drift apart, a pass deletes what a lint
protects.  So the classification lives HERE, every consumer imports it,
and a scanner test (``tests/test_opmeta.py``) fails any module that
grows its own effect-op list.

The primitive facts come from the op registry itself
(:class:`paddle_tpu.ops.registry.OpDef`: ``host``, ``uses_rng``,
``stateful_outputs``) plus the runtime families the registry cannot
express per-opdef (readers, CSP channels, persistence ops).
"""

from __future__ import annotations

from paddle_tpu import framework

__all__ = ["EFFECT_OP_TYPES", "ELEMENTWISE_PURE_OPS", "sub_blocks",
           "has_sub_block", "has_effects", "is_pure", "is_host",
           "uses_rng", "stateful_output_names", "needs_rng_key",
           "writes_persistable"]

#: op families with effects beside their dataflow outputs even though
#: their opdef declares none: executor-rewritten ops, host I/O,
#: CSP/channel runtime ops, counters mutated in place (mirrors
#: ``executor._SKIP_OPS`` + the runtime channel family).  This is the
#: ONE owning definition — the dead-op lint and the DCE pass both
#: import it (scanner-enforced).
EFFECT_OP_TYPES = frozenset({
    "feed", "fetch", "read", "print", "assert", "save", "load",
    "save_combine", "load_combine", "send", "recv", "go", "select",
    "channel_send", "channel_recv", "channel_close", "increment",
})

#: pure elementwise op types the fusion pass may collapse into one
#: traced closure: output shape == X's shape, no RNG, no state, no
#: sub-block, value depends only on the listed inputs.  Deliberately a
#: closed allow-list (not "everything pure"): fusion changes trace
#: structure, so each member is vouched for individually.
ELEMENTWISE_PURE_OPS = frozenset({
    "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "abs", "square",
    "softsign", "softplus", "relu6", "leaky_relu", "elu", "gelu",
    "hard_sigmoid", "swish", "brelu", "pow", "reciprocal", "floor",
    "ceil", "round", "sin", "cos", "clip", "scale",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "cast", "fill_zeros_like", "label_smooth",
})


def sub_blocks(op):
    """The Block attrs an op carries (while/cond/recurrent bodies)."""
    for a in op.attrs.values():
        if isinstance(a, framework.Block):
            yield a


def has_sub_block(op):
    return any(True for _ in sub_blocks(op))


def has_effects(op, registry=None):
    """True when removing this op could change anything beside its
    dataflow outputs: host ops, declared in-place state updates, RNG
    consumers, reader/CSP/persistence families, sub-block carriers.
    The dead-op lint's exemption predicate AND the DCE pass's removal
    guard — one definition, so they can never disagree."""
    if registry is None:
        from paddle_tpu.ops import registry
    if op.type in EFFECT_OP_TYPES or op.type.startswith("create_"):
        return True
    opdef = registry.lookup(op.type)
    if opdef is not None and (opdef.host or opdef.stateful_outputs or
                              opdef.uses_rng):
        return True
    return has_sub_block(op)


def writes_persistable(op, block):
    """True when any output var of ``op`` is persistable in ``block``'s
    scope chain — a persistable write IS an effect (state survives the
    step), whatever the opdef says."""
    for n in op.output_arg_names:
        if not n:
            continue
        try:
            v = block.var(n)
        except KeyError:
            continue
        if getattr(v, "persistable", False):
            return True
    return False


def is_pure(op, block, registry=None):
    """Provably pure: no effects, no persistable writes — removing or
    deduplicating the op is observationally invisible as long as its
    outputs are re-derivable.  The CSE/fold eligibility predicate."""
    if registry is None:
        from paddle_tpu.ops import registry
    return not has_effects(op, registry) and \
        not writes_persistable(op, block)


def is_host(op, registry=None):
    if registry is None:
        from paddle_tpu.ops import registry
    opdef = registry.lookup(op.type)
    return opdef is not None and opdef.host


def uses_rng(op, registry=None):
    if registry is None:
        from paddle_tpu.ops import registry
    opdef = registry.lookup(op.type)
    return opdef is not None and opdef.uses_rng


def stateful_output_names(op, registry=None):
    """Names this op updates IN PLACE per its opdef's
    ``stateful_outputs`` declaration (the donation planner's facts)."""
    if registry is None:
        from paddle_tpu.ops import registry
    opdef = registry.lookup(op.type)
    if opdef is None or not opdef.stateful_outputs:
        return []
    return [n for slot in opdef.stateful_outputs
            for n in op.output(slot) if n]


def needs_rng_key(op, registry=None):
    """Whether the executor must hand this op a folded RNG key at
    trace time: declared RNG consumers, sub-block carriers (their body
    ops fold keys from the op's key), and unknown op types (no opdef —
    assume the worst).  Ops outside this set never call
    ``ctx.rng_key()`` (the registry contract: auto-vjp refuses RNG
    forwards, so ``*_grad`` of an RNG op always has an explicit,
    key-free grad lowering) — the opt pipeline's rng-plan fact lets
    ``lower_block`` skip their per-op ``jax.random.fold_in``, which is
    a traced threefry computation each, without perturbing the keys
    RNG ops receive (the counter still advances one slot per op)."""
    if registry is None:
        from paddle_tpu.ops import registry
    opdef = registry.lookup(op.type)
    if opdef is None:
        if op.type.endswith("_grad"):
            fwd = registry.lookup(op.type[:-len("_grad")])
            if fwd is not None:
                # grads of RNG forwards carry explicit key-free
                # lowerings (registry contract), but stay conservative
                # and key them anyway; grads of key-free forwards
                # auto-vjp the forward, which never sees a key
                return bool(fwd.uses_rng)
        return True
    return bool(opdef.uses_rng) or has_sub_block(op)
