"""Health-aware HTTP router over a fleet of serving replicas.

A thin front-end: discovers live replicas from the master's lease table
(or a static list), spreads `/predict` traffic by least-outstanding
requests, and on connection failure / retryable 503 / lease expiry
retries the request on a *different* replica under a
:class:`~paddle_tpu.fault.RetryPolicy` with full jitter — bounded end
to end by the caller's deadline, which rides the ``X-Deadline-Ms``
header into the replica's own :class:`MicroBatcher` timeout so a
failover chain can never spend more than the original budget.  The
caller's ``X-Request-Id`` (minted here when absent) is forwarded on
every attempt, making one request traceable across replicas in their
``/trace`` rings.

The router holds no model state and does no JSON re-encoding of predict
bodies — request and reply bytes pass through verbatim — so it stays
cheap enough to front many replicas from one process.

It is also the fleet's observability vantage point
(``docs/observability.md`` § Fleet observability):
``/metrics?fleet=1`` federates every replica's registry into one
exposition (``obs.aggregate.FleetScraper``; dead replicas marked stale,
never fatal), ``/trace?fleet=1`` assembles every process's span ring
into one clock-normalized Chrome timeline, ``/spans`` serves the
router's own ring in the same scrape shape, and an optional SLO
watchdog (``slo_spec=`` / ``PADDLE_TPU_SLO``) evaluates declarative
objectives over the runtime metrics in a background thread, surfacing
its breach log under ``/stats``.

Streamed ``/generate`` traffic is *session-aware*: each request mints
(or carries) a session id tracked in a bounded
:class:`~paddle_tpu.fleet.sessions.SessionTable` — owning replica,
prompt hash, tokens delivered — so follow-ups and resumes route back
to the owner (affine routing), and when the owner dies mid-stream the
router re-prefills ``prompt + tokens_so_far`` on a survivor with a
``resume_from`` index and splices the continuation into the SAME
client response, deduplicating on the monotone ``token_index`` every
event carries (greedy decode is deterministic, so the splice is
token-identical and exactly-once).

Failpoints: ``fleet.route.blackhole`` fires per forward attempt (armed
``error`` turns the attempt into a connection failure — the drill for a
partitioned replica the lease hasn't expired yet);
``gen.session.kill_owner`` fires per relayed token (armed ``error``
simulates the owning replica dying after producing that token — the
mid-stream failover drill); ``gen.stream.truncate`` fires per upstream
stream read (armed ``error`` tears the stream mid-chunk — the torn
transport drill).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from urllib.parse import parse_qs, urlsplit

from paddle_tpu.obs import aggregate as _aggregate
from paddle_tpu.obs import slo as _slo
from paddle_tpu.obs import trace as _trace
from paddle_tpu.obs.trace import span as _span

__all__ = ["FleetRouter"]


class _NoReplicas(ConnectionError):
    """No live replica to route to (retryable: one may re-register)."""


class _Transient(ConnectionError):
    """Upstream replied retryable (503/504-class): fail over."""


class _DeadlineExhausted(RuntimeError):
    """The caller's end-to-end budget ran out (non-retryable)."""


class _StreamAborted(RuntimeError):
    """The DOWNSTREAM client vanished mid-relay (non-retryable: there is
    nobody left to fail over for)."""


class FleetRouter:
    """Route `/predict` across replicas with health-aware failover.

    ``master_addr`` enables discovery from
    :meth:`MasterService.list_replicas` (polled every
    ``poll_interval``); ``replicas`` is the static-list alternative.
    ``retry`` defaults to full-jitter exponential backoff; the
    effective deadline per request is the caller's ``X-Deadline-Ms``
    when present, else ``default_deadline`` seconds.
    """

    def __init__(self, master_addr=None, replicas=None, host="127.0.0.1",
                 port=0, retry=None, poll_interval=0.25,
                 default_deadline=30.0, attempt_timeout=30.0,
                 down_cooldown=1.0, slo_spec=None, scrape_timeout=2.0,
                 session_capacity=1024):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from paddle_tpu.fault.retry import RetryPolicy, parse_hostport
        from paddle_tpu.fleet.sessions import SessionTable
        if master_addr is None and not replicas:
            raise ValueError("FleetRouter needs master_addr or replicas")
        self._master_addr = master_addr
        self._master = None
        self._retry = retry or RetryPolicy(
            max_attempts=6, base_delay=0.05, max_delay=0.5, jitter="full")
        self._default_deadline = float(default_deadline)
        self._attempt_timeout = float(attempt_timeout)
        self._down_cooldown = float(down_cooldown)
        self._poll_interval = float(poll_interval)
        self._lock = threading.Lock()
        # addr ("host:port") -> per-replica health/load book-keeping
        self._table = {}
        for a in replicas or []:
            h, p = parse_hostport(a)
            self._table[f"{h}:{p}"] = self._fresh_entry(f"{h}:{p}")
        self._static = master_addr is None
        self._stop = threading.Event()
        # per-handler-thread keep-alive connections to replicas (the
        # replica side speaks HTTP/1.1 exactly so the router does not
        # pay a TCP handshake + server thread spawn per forwarded
        # request); entries die with their handler thread
        self._tl = threading.local()
        # last N failovers: (request_id, failed addrs..., served-by) —
        # the drill's evidence that a specific request changed replicas
        self.failover_log = collections.deque(maxlen=256)
        # live generative sessions: affine routing + mid-stream resume
        # state (evicted on terminal delivery; bounded, orphan-counting)
        self.sessions = SessionTable(capacity=session_capacity)
        # fleet observability plane: federation scraper over the
        # routing table (obs.aggregate) + optional SLO watchdog
        # (obs.slo; explicit spec wins over PADDLE_TPU_SLO)
        self._scrape_timeout = float(scrape_timeout)
        self._scraper = _aggregate.FleetScraper(
            self.scrape_targets, timeout=self._scrape_timeout)
        self._slo = (_slo.SLOWatchdog(slo_spec) if slo_spec is not None
                     else _slo.watchdog_from_env())
        if self._slo is not None:
            self._slo.start()
        # graceful-degradation ladder (admission control): level 0
        # admits everything; a controller raises the level to shed a
        # growing fraction of arrivals at the door with 429 +
        # Retry-After INSTEAD of queueing them into a deadline timeout
        self._admission_lock = threading.Lock()
        self._admission = {"level": 0, "shed_fraction": 0.0,
                           "retry_after_s": 1.0, "reason": "",
                           "since_unix": time.time()}
        self._admission_acc = 0.0  # Bresenham-style shed accumulator
        _trace.set_process_name("router")
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply_raw(self, code, body, content_type,
                           extra_headers=None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header("X-Request-Id", rid)
                self.end_headers()
                self.wfile.write(body)

            def _reply(self, code, obj):
                self._reply_raw(code, json.dumps(obj).encode(),
                                "application/json")

            def _error(self, code, etype, message, retryable,
                       **extra):
                body = {"error": {"type": etype, "message": message},
                        "retryable": retryable}
                body.update(extra)
                self._reply(code, body)

            def do_GET(self):
                self._request_id = (self.headers.get("X-Request-Id")
                                    or "").strip() or None
                parts = urlsplit(self.path)
                path = parts.path
                query = parse_qs(parts.query)
                # ?fleet=1 flips /metrics and /trace from this
                # process's view to the FEDERATED one (every replica
                # scraped, merged, labelled)
                fleet = (query.get("fleet", ["0"])[0].lower()
                         not in ("", "0", "false", "no"))
                if path in ("/health", "/healthz"):
                    self._reply(200, {"status": "ok"})
                elif path == "/readyz":
                    n = len(router.live_replicas())
                    if n > 0:
                        self._reply(200, {"status": "ready",
                                          "replicas": n})
                    else:
                        self._error(503, "no_replicas",
                                    "no live replicas in the routing "
                                    "table", retryable=True)
                elif path == "/replicas":
                    self._reply(200, {"replicas": router.table()})
                elif path == "/stats":
                    from paddle_tpu import profiler as _profiler
                    snap = _profiler.runtime_metrics.snapshot()
                    snap["router"] = {
                        "replicas": router.table(),
                        "failovers": [list(f) for f in
                                      router.failover_log],
                        "admission": router.admission_state(),
                        "sessions": router.sessions.snapshot(),
                    }
                    # per-replica MFU / HBM headroom from the latest
                    # federation pass (empty before the first
                    # /metrics?fleet=1 scrape — never blocks on one)
                    snap["fleet_perf"] = router._scraper.last_perf()
                    if router._slo is not None:
                        snap["slo"] = router._slo.state()
                    self._reply(200, snap)
                elif path == "/metrics":
                    if fleet:
                        self._reply_raw(
                            200, router.fleet_metrics().encode(),
                            _aggregate.CONTENT_TYPE)
                        return
                    from paddle_tpu.obs import prom as _prom
                    self._reply_raw(
                        200, _prom.render_prometheus().encode(),
                        _prom.CONTENT_TYPE)
                elif path == "/trace":
                    if fleet:
                        self._reply_raw(
                            200,
                            json.dumps(router.fleet_trace()).encode(),
                            "application/json")
                        return
                    self._reply_raw(200,
                                    _trace.dump_chrome_trace().encode(),
                                    "application/json")
                elif path == "/spans":
                    # the router's own ring, in the same scrape shape
                    # replicas serve (so a higher-level aggregator can
                    # treat the router as just another process)
                    self._reply(200, _trace.snapshot_payload())
                else:
                    self._error(404, "not_found", self.path,
                                retryable=False)

            def do_POST(self):
                from paddle_tpu.fault.retry import parse_deadline_ms
                self._request_id = (self.headers.get("X-Request-Id")
                                    or "").strip() or _trace.new_trace_id()
                if "Content-Length" not in self.headers:
                    # no declared length (absent or chunked): the body
                    # can't be read, so don't burn a routed attempt
                    # delivering an empty one — reject here
                    self.close_connection = True
                    self._error(411, "length_required",
                                "POST requires Content-Length",
                                retryable=False)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                except ValueError:
                    self.close_connection = True
                    self._error(400, "bad_request",
                                "invalid Content-Length header",
                                retryable=False)
                    return
                if self.path not in ("/predict", "/run", "/generate"):
                    self._error(404, "not_found", self.path,
                                retryable=False)
                    return
                try:
                    budget = parse_deadline_ms(
                        self.headers.get("X-Deadline-Ms"))
                except ValueError:
                    self._error(400, "bad_request",
                                f"invalid X-Deadline-Ms header: "
                                f"{self.headers.get('X-Deadline-Ms')!r}",
                                retryable=False)
                    return
                if budget is None:
                    budget = router._default_deadline
                # admission control runs BEFORE any routing work: a
                # shed request costs the fleet one header parse, not a
                # queued attempt that burns its own deadline
                shed = router.admit(budget)
                if shed is not None:
                    self._reply_raw(*shed)
                    return
                if self.path == "/generate":
                    # streamed generation: chunks are forwarded to the
                    # caller AS the replica produces them — time-to-
                    # first-token survives the fleet hop
                    router.route_stream(self, raw, self._request_id,
                                        budget)
                    return
                code, body, ctype, headers = router.route(
                    self.path, raw, self._request_id, budget)
                self._reply_raw(code, body, ctype, headers)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._server.server_address
        self._poll_thread = None
        if not self._static:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="fleet-router-discovery")
            self._poll_thread.start()

    # -- routing table -----------------------------------------------------
    @staticmethod
    def _fresh_entry(addr, replica_id=None):
        return {"id": replica_id or addr, "addr": addr, "outstanding": 0,
                "requests": 0, "failures": 0, "down_until": 0.0}

    def _poll_loop(self):
        while not self._stop.wait(self._poll_interval):
            self.refresh()

    def refresh(self):
        """One discovery pass against the master (no-op in static
        mode): live leases enter the table, expired ones leave it."""
        from paddle_tpu import profiler as _profiler
        if self._static:
            return
        try:
            if self._master is None:
                from paddle_tpu.parallel.master import MasterClient
                self._master = MasterClient(self._master_addr)
            live = self._master.list_replicas()
        except Exception:
            return  # master blip: keep routing on the current table
        with self._lock:
            seen = set()
            for rec in live:
                addr = rec["addr"]
                seen.add(addr)
                entry = self._table.get(addr)
                if entry is None:
                    self._table[addr] = self._fresh_entry(addr, rec["id"])
                else:
                    entry["id"] = rec["id"]
            for addr in [a for a in self._table if a not in seen]:
                del self._table[addr]
            _profiler.runtime_metrics.set_gauge("fleet.replicas_live",
                                                len(self._table))

    def live_replicas(self):
        """Addresses currently eligible for new traffic."""
        now = time.monotonic()
        with self._lock:
            return [a for a, e in self._table.items()
                    if e["down_until"] <= now]

    def table(self):
        """Per-replica health/load snapshot (the `/replicas` body)."""
        now = time.monotonic()
        with self._lock:
            return {a: {"id": e["id"], "outstanding": e["outstanding"],
                        "requests": e["requests"],
                        "failures": e["failures"],
                        "down": e["down_until"] > now}
                    for a, e in self._table.items()}

    def _pick(self, tried, prefer=None):
        """Least-outstanding live replica, preferring one not yet tried
        by THIS request; falls back to tried replicas only when every
        live one has failed this chain (single-replica fleets still
        retry).  ``prefer`` (a session's owning replica — affine
        routing) wins outright while it is live and not yet tried."""
        now = time.monotonic()
        with self._lock:
            if prefer is not None and prefer not in tried:
                e = self._table.get(prefer)
                if e is not None and e["down_until"] <= now:
                    return prefer
            live = [(e["outstanding"], a) for a, e in self._table.items()
                    if e["down_until"] <= now]
            if not live:
                # every replica is cooling down: routing to a maybe-dead
                # replica beats refusing while the table is non-empty
                live = [(e["outstanding"], a)
                        for a, e in self._table.items()]
        if not live:
            raise _NoReplicas("no live replicas in the routing table")
        untried = [(o, a) for o, a in live if a not in tried]
        pool = untried or live
        # random tie-break: a deterministic (outstanding, addr) sort
        # would pin ALL low-concurrency traffic to the smallest address
        import random
        random.shuffle(pool)
        # equal-outstanding ties break toward the replica with the most
        # HBM headroom in the latest federation pass (cost-model
        # placement: the replica closest to OOM is the worst home for
        # new work); replicas without scrape evidence sort neutral
        perf = self._scraper.last_perf()

        def load_key(e):
            o, a = e
            head = (perf.get(a) or {}).get("hbm.headroom_bytes")
            return (o, 0.0 if head is None else -float(head))

        pool.sort(key=load_key)
        return pool[0][1]

    def _mark_down(self, addr):
        """Short cooldown after a connection-level failure, so the hot
        path stops picking a dead replica before the lease expires."""
        with self._lock:
            e = self._table.get(addr)
            if e is not None:
                e["failures"] += 1
                e["down_until"] = time.monotonic() + self._down_cooldown

    # -- fleet observability plane -----------------------------------------
    def scrape_targets(self):
        """Federation scrape set: EVERY replica in the table, including
        cooling-down ones — the scrape itself decides staleness by
        failing, and a corpse must show up as ``stale=1``, not vanish
        from the fleet view before its lease expires."""
        with self._lock:
            return [(a, e["id"]) for a, e in sorted(self._table.items())]

    def fleet_metrics(self):
        """The federated ``/metrics?fleet=1`` body: every replica's
        registry under ``replica=`` labels plus fleet rollups; dead
        replicas are marked stale, never fatal."""
        text, _scrapes = self._scraper.federate()
        return text

    def fleet_trace(self):
        """The assembled ``/trace?fleet=1`` body: the router's own span
        ring merged with every reachable replica's (clock-skew
        normalized against this process's send/recv envelopes,
        scraped concurrently), one timeline row per process.
        Unreachable replicas are reported in
        ``fleetAssembly.failures`` — a hard-killed replica must not
        take the fleet timeline down with it."""
        sources = [{"source": "router",
                    "payload": _trace.snapshot_payload(),
                    "envelope": None}]
        sources.extend(_aggregate.fetch_spans_many(
            [addr for addr, _rid in self.scrape_targets()],
            timeout=self._scrape_timeout))
        return _aggregate.assemble_fleet_trace(sources)

    # -- admission control (graceful-degradation ladder) -------------------
    def set_admission(self, level, shed_fraction, retry_after_s=1.0,
                      reason=""):
        """Set the degradation rung: shed ``shed_fraction`` of incoming
        POSTs at the door with ``429`` + ``Retry-After:
        retry_after_s`` (clamped per request to the caller's own
        ``X-Deadline-Ms`` budget).  Level 0 / fraction 0 admits
        everything.  Called by the fleet controller as SLO pressure
        builds and recedes; ``reason`` lands in ``/stats`` so an
        operator can see WHY the fleet is shedding."""
        from paddle_tpu import profiler as _profiler
        level = max(0, int(level))
        shed_fraction = min(1.0, max(0.0, float(shed_fraction)))
        with self._admission_lock:
            changed = level != self._admission["level"]
            self._admission = {
                "level": level,
                "shed_fraction": shed_fraction,
                "retry_after_s": max(0.0, float(retry_after_s)),
                "reason": str(reason),
                "since_unix": (time.time() if changed
                               else self._admission["since_unix"]),
            }
            if changed:
                self._admission_acc = 0.0
        _profiler.runtime_metrics.set_gauge("fleet.admission_level",
                                            level)

    def admission_state(self):
        """The current rung (the ``/stats`` ``router.admission`` body)."""
        with self._admission_lock:
            return dict(self._admission)

    def admit(self, budget):
        """Admission decision for ONE arriving request: None to admit,
        or a ready-to-send ``(429, body, content_type, headers)`` shed.
        Sheds are spread evenly through the arrival stream (error-
        accumulator, not random draws: a 25% shed rung bounces exactly
        every 4th request, so a short probe burst can never be
        all-unlucky), and the ``Retry-After`` hint is clamped to the
        caller's remaining deadline budget — a hint the caller cannot
        possibly wait out is just a slower timeout."""
        with self._admission_lock:
            frac = self._admission["shed_fraction"]
            if frac <= 0.0:
                return None
            self._admission_acc += frac
            if self._admission_acc < 1.0:
                return None
            self._admission_acc -= 1.0
            level = self._admission["level"]
            reason = self._admission["reason"]
            hint = self._admission["retry_after_s"]
        from paddle_tpu import profiler as _profiler
        _profiler.runtime_metrics.inc("fleet.admission_shed")
        retry_after = hint if budget is None \
            else max(0.0, min(hint, float(budget)))
        body = json.dumps({
            "error": {"type": "admission_shed",
                      "message": f"fleet shedding at degradation level "
                                 f"{level}" + (f": {reason}" if reason
                                               else "")},
            "retryable": True,
            "degrade_level": level,
            "retry_after_s": retry_after,
        }).encode()
        return 429, body, "application/json", \
            {"Retry-After": f"{retry_after:.3f}"}

    def _shed_hint(self, deadline_at):
        """Retry-After for a router-GENERATED shed (503/504): the
        admission ladder's current pacing hint, clamped to the caller's
        remaining budget when any is left (a caller whose budget is
        gone gets the unclamped hint for its NEXT request)."""
        with self._admission_lock:
            hint = self._admission["retry_after_s"] or 1.0
        remaining = deadline_at - time.monotonic()
        if remaining > 0:
            hint = min(hint, remaining)
        return max(0.0, hint)

    def _alternative_with_headroom(self, addr):
        """True when the latest federation pass shows a DIFFERENT live
        replica plausibly able to absorb a request the replica at
        ``addr`` just shed with 429 — the gate on treating an upstream
        429 as retryable-elsewhere.  Requires scrape EVIDENCE: before
        the first pass (or when no candidate answered it) the answer is
        False and the 429 passes through verbatim, so clients back off
        instead of the router hammering a uniformly saturated fleet."""
        ok = self._scraper.last_ok()
        if not ok:
            return False
        now = time.monotonic()
        with self._lock:
            me = self._table.get(addr)
            my_out = (me["outstanding"] if me is not None
                      else float("inf"))
            for a, e in self._table.items():
                if a == addr or e["down_until"] > now or a not in ok:
                    continue
                if e["outstanding"] <= my_out:
                    return True
        return False

    # -- request path ------------------------------------------------------
    def route(self, path, raw, request_id, budget):
        """Forward one request; returns ``(status, body, content_type,
        extra_headers)``.  Every terminal failure the router
        *generates* is a structured retryable error with a
        ``Retry-After`` pacing hint — the client's own policy decides
        what to do."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault.retry import RetryError
        deadline_at = time.monotonic() + budget
        tried = []
        t0 = time.perf_counter()

        def attempt():
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                raise _DeadlineExhausted(
                    f"deadline ({budget * 1e3:.0f}ms) exhausted after "
                    f"{len(tried)} attempt(s)")
            addr = self._pick(tried)
            tried.append(addr)
            with _span("fleet.attempt", replica=addr,
                       attempt=len(tried)):
                return self._forward(addr, path, raw, request_id,
                                     remaining)

        def on_retry(attempt_no, exc, delay):
            _profiler.runtime_metrics.inc("fleet.retries")

        try:
            with _trace.trace_context(request_id), \
                    _span("fleet.request", request_id=request_id,
                          path=path):
                status, body, ctype, headers = self._retry.call(
                    attempt, on_retry=on_retry, deadline=budget)
            if status == 200:
                _profiler.runtime_metrics.inc("fleet.requests_ok")
                if len(tried) > 1:
                    # the request changed replicas and still completed:
                    # the headline failover event, logged for forensics
                    _profiler.runtime_metrics.inc("fleet.failovers")
                    self.failover_log.append(
                        (request_id, *tried))
            return status, body, ctype, headers
        except _DeadlineExhausted as e:
            _profiler.runtime_metrics.inc("fleet.shed")
            return self._shed(504, "deadline_exceeded", str(e), tried,
                              retry_after=self._shed_hint(deadline_at))
        except RetryError as e:
            e.history = list(tried)
            _profiler.runtime_metrics.inc("fleet.shed")
            if isinstance(e.last, _NoReplicas):
                return self._shed(
                    503, "no_replicas", str(e.last), tried,
                    retry_after=self._shed_hint(deadline_at))
            return self._shed(503, "upstream_unavailable",
                              f"all failover attempts failed: {e.last}",
                              tried,
                              retry_after=self._shed_hint(deadline_at))
        except _NoReplicas as e:
            _profiler.runtime_metrics.inc("fleet.shed")
            return self._shed(503, "no_replicas", str(e), tried,
                              retry_after=self._shed_hint(deadline_at))
        finally:
            _profiler.runtime_metrics.observe(
                "fleet.request_seconds", time.perf_counter() - t0)

    # -- streamed generation (/generate) -----------------------------------
    def route_stream(self, handler, raw, request_id, budget):
        """Forward one ``/generate`` request, relaying response chunks
        to ``handler`` AS the replica produces them (no body
        buffering — the first token reaches the caller while the
        replica is still decoding).

        Failover semantics: retryable failures BEFORE the first
        forwarded byte (connection failure, retryable 503/504, upstream
        dying without producing a chunk) fail over to a sibling replica
        exactly like :meth:`route`.  MID-stream, the request is a
        tracked *session*: when the owning replica dies (or hands the
        stream back with a drain-time ``migrate`` tail), the router
        re-submits ``prompt + tokens_delivered`` with a ``resume_from``
        index to a survivor and splices the deterministic continuation
        into the same client response — the caller sees one
        uninterrupted, duplicate-free token sequence.  Only when every
        resume attempt fails does the relay terminate with a structured
        trailing error line (now carrying ``token_index`` +
        ``retryable``)."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault.retry import RetryError
        from paddle_tpu.fleet import sessions as _sessions
        deadline_at = time.monotonic() + budget
        tried = []
        t0 = time.perf_counter()
        # parse once for the session registry; malformed bodies forward
        # verbatim (the replica owns request validation and the 400)
        sess = None
        try:
            req = json.loads(raw)
            prompt = [int(t) for t in req.get("prompt") or []]
            if prompt:
                sid = str(req.get("session_id")
                          or _sessions.new_session_id())
                sess = {"sid": sid, "prompt": prompt,
                        "max_new": int(req.get("max_new_tokens", 16)),
                        "eos_id": req.get("eos_id"),
                        "stream": bool(req.get("stream", True)),
                        "resume_from0": int(req.get("resume_from", 0)
                                            or 0),
                        "tokens": [], "sent_headers": False,
                        "resumed": False}
                self.sessions.begin(sid, None, prompt, sess["max_new"],
                                    delivered=sess["resume_from0"])
        except (AttributeError, TypeError, ValueError):
            sess = None

        def attempt():
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                raise _DeadlineExhausted(
                    f"deadline ({budget * 1e3:.0f}ms) exhausted after "
                    f"{len(tried)} attempt(s)")
            # affine routing: a follow-up/resume for a known session
            # goes back to the owning replica when it is still live
            prefer = (self.sessions.owner(sess["sid"])
                      if sess is not None else None)
            addr = self._pick(tried, prefer=prefer)
            tried.append(addr)
            if sess is not None:
                self.sessions.note(sess["sid"], replica=addr)
            with _span("fleet.attempt", replica=addr,
                       attempt=len(tried)):
                return self._forward_stream(addr, handler, raw,
                                            request_id, remaining,
                                            sess=sess)

        def on_retry(attempt_no, exc, delay):
            _profiler.runtime_metrics.inc("fleet.retries")

        try:
            with _trace.trace_context(request_id), \
                    _span("fleet.request", request_id=request_id,
                          path="/generate"):
                outcome = self._retry.call(attempt, on_retry=on_retry,
                                           deadline=budget)
            if sess is not None and outcome == "passthrough":
                self.sessions.finish(sess["sid"])
            if outcome == "ok":
                # only CLEAN completions count: a relay terminated by a
                # mid-stream upstream death delivered an error tail,
                # not a successful request
                _profiler.runtime_metrics.inc("fleet.requests_ok")
                if len(tried) > 1:
                    _profiler.runtime_metrics.inc("fleet.failovers")
                    self.failover_log.append((request_id, *tried))
            return
        except _StreamAborted:
            # downstream client hung up mid-stream: nothing to reply
            # to (the session entry stays until the orphan eviction —
            # a reconnecting client may still resume it)
            handler.close_connection = True
            return
        except _DeadlineExhausted as e:
            _profiler.runtime_metrics.inc("fleet.shed")
            code, body, ctype, headers = self._shed(
                504, "deadline_exceeded", str(e), tried,
                retry_after=self._shed_hint(deadline_at))
        except RetryError as e:
            e.history = list(tried)
            _profiler.runtime_metrics.inc("fleet.shed")
            if isinstance(e.last, _NoReplicas):
                code, body, ctype, headers = self._shed(
                    503, "no_replicas", str(e.last), tried,
                    retry_after=self._shed_hint(deadline_at))
            else:
                code, body, ctype, headers = self._shed(
                    503, "upstream_unavailable",
                    f"all failover attempts failed: {e.last}", tried,
                    retry_after=self._shed_hint(deadline_at))
        except _NoReplicas as e:
            _profiler.runtime_metrics.inc("fleet.shed")
            code, body, ctype, headers = self._shed(
                503, "no_replicas", str(e), tried,
                retry_after=self._shed_hint(deadline_at))
        finally:
            _profiler.runtime_metrics.observe(
                "fleet.request_seconds", time.perf_counter() - t0)
        if sess is not None and sess["sent_headers"]:
            # the 200 + chunked headers are already downstream: the
            # terminal failure must ride the stream as an error TAIL,
            # not a second status line
            try:
                err = json.loads(body).get("error") or {}
            except ValueError:
                err = {}
            self._finish_stream(
                handler,
                error=err.get("message", "stream failed"),
                etype=err.get("type", "upstream_died"),
                token_index=sess["resume_from0"] + len(sess["tokens"]),
                retryable=True)
            self.sessions.finish(sess["sid"])
            return
        if sess is not None:
            self.sessions.finish(sess["sid"])
        handler._reply_raw(code, body, ctype, headers)

    def _forward_stream(self, addr, handler, raw, request_id, remaining,
                        sess=None):
        """One streamed attempt; returns ``"ok"`` when the relay ran to
        clean completion, ``"upstream_error"`` when it relayed a
        terminal error tail, ``"upstream_died"`` when a SESSION-less
        relay was terminated mid-stream, ``"passthrough"`` when the
        upstream reply was passed through verbatim (permanent error).

        With session state (``sess``), a mid-stream owner death, a
        retryable error tail, or a drain-time ``migrate`` tail raises
        ``_Transient`` INSTEAD of terminating the relay: the retry
        policy re-enters this method with ``sess["resumed"]`` set, the
        request body is rebuilt as ``prompt + tokens_delivered`` with a
        ``resume_from`` index, and the survivor's continuation is
        spliced into the SAME downstream chunked response — exactly-once
        token delivery across replica death, keyed on the monotone
        ``token_index``."""
        import http.client

        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault import chaos
        try:
            chaos.fire("fleet.route.blackhole", replica=addr)
        except chaos.FaultInjected as e:
            self._mark_down(addr)
            raise _Transient(f"route to {addr} blackholed") from e
        sid = sess["sid"] if sess is not None else None
        resumed = bool(sess and sess["resumed"])
        body = raw if sess is None else self._resume_body(sess, raw)
        with self._lock:
            entry = self._table.get(addr)
            if entry is not None:
                entry["outstanding"] += 1
                entry["requests"] += 1
        timeout = min(remaining, self._attempt_timeout)
        headers = {
            "Content-Type": "application/json",
            "X-Request-Id": request_id,
            "X-Deadline-Ms": str(int(remaining * 1000)),
        }

        def resume_or_die(msg, mark_down=True, cause=None):
            # one mid-stream fault, one decision: sessions fail over
            # (the policy re-enters with a rebuilt resume body);
            # session-less relays terminate with a legacy error tail
            self._drop_conn(addr)
            if mark_down:
                self._mark_down(addr)
            if sess is not None:
                if sess["sent_headers"] and \
                        len(sess["tokens"]) >= sess["max_new"]:
                    # every budgeted token is already delivered — only
                    # the done tail was lost: synthesize it, no resume
                    self._synthesize_done(handler, sess)
                    return "ok"
                sess["resumed"] = True
                _profiler.runtime_metrics.inc("gen.session.resumes")
                raise _Transient(
                    f"session {sid}: {msg} — resuming from token "
                    f"{sess['resume_from0'] + len(sess['tokens'])}"
                ) from cause
            self._finish_stream(handler, error=msg)
            return "upstream_died"

        try:
            for retry_fresh in (False, True):
                reused, conn = self._pooled_conn(addr, timeout)
                try:
                    conn.request("POST", "/generate", body, headers)
                    resp = conn.getresponse()
                    break
                except (OSError, http.client.HTTPException) as e:
                    self._drop_conn(addr)
                    if reused and not retry_fresh:
                        continue
                    self._mark_down(addr)
                    raise ConnectionError(
                        f"replica {addr} unreachable: {e}") from e
            if resp.status != 200:
                rbody = resp.read()
                from paddle_tpu.fault.retry import parse_retry_after
                hint_raw = resp.getheader("Retry-After")
                if resp.will_close:
                    self._drop_conn(addr)
                try:
                    parsed = json.loads(rbody)
                except ValueError:
                    parsed = {"retryable":
                              resp.status in (429, 502, 503, 504)}
                if parsed.get("retryable"):
                    if resp.status == 429 and \
                            not self._alternative_with_headroom(addr) \
                            and not (sess and sess["sent_headers"]):
                        # no sibling with scraped headroom: the 429 +
                        # Retry-After passes through verbatim
                        handler._reply_raw(
                            resp.status, rbody, "application/json",
                            {"Retry-After": hint_raw} if hint_raw
                            else None)
                        return "passthrough"
                    err = parsed.get("error") or {}
                    exc = _Transient(
                        f"replica {addr} replied {resp.status} "
                        f"{err.get('type', 'retryable')}: "
                        f"{err.get('message', '')}")
                    hint = parse_retry_after(hint_raw)
                    if hint is not None:
                        exc.retry_after = hint
                    raise exc
                if sess is not None and sess["sent_headers"]:
                    # a resume attempt hit a PERMANENT error (e.g.
                    # resume_unsupported) after the 200 went downstream:
                    # terminate the stream with a non-retryable tail
                    err = parsed.get("error") or {}
                    self._finish_stream(
                        handler,
                        error=err.get("message",
                                      f"upstream replied {resp.status}"),
                        etype=err.get("type", "upstream_error"),
                        token_index=(sess["resume_from0"]
                                     + len(sess["tokens"])),
                        retryable=False)
                    self.sessions.finish(sid)
                    return "upstream_error"
                handler._reply_raw(resp.status, rbody,
                                   "application/json")
                return "passthrough"
            # the replica holds its 200 until the first token exists,
            # so the first line is imminent; reading it BEFORE sending
            # downstream headers keeps this attempt fully retryable
            try:
                first = resp.readline()
            except (OSError, http.client.HTTPException) as e:
                self._drop_conn(addr)
                self._mark_down(addr)
                raise ConnectionError(
                    f"replica {addr} died before streaming: {e}") from e
            if not first:
                self._drop_conn(addr)
                self._mark_down(addr)
                raise _Transient(
                    f"replica {addr} closed the stream before the "
                    f"first chunk")
            ctype = resp.getheader("Content-Type",
                                   "application/x-ndjson")
            if sess is None:
                return self._relay_stream_verbatim(
                    addr, handler, request_id, resp, first, ctype)
            # session-aware relay: parse each upstream line, dedupe on
            # token_index, convert resumable faults into failover
            terminal = None
            line = first
            while True:
                try:
                    obj = json.loads(line)
                except ValueError:
                    obj = None
                if obj is not None and "token" in obj \
                        and "index" in obj:
                    delivered = (sess["resume_from0"]
                                 + len(sess["tokens"]))
                    idx = obj["index"]
                    if idx < delivered:
                        # replayed prefix after a resume: exactly-once
                        # delivery is THIS drop
                        _profiler.runtime_metrics.inc(
                            "gen.session.dedup_drops")
                    elif idx > delivered:
                        return resume_or_die(
                            f"token_index gap (got {idx}, expected "
                            f"{delivered})", mark_down=False)
                    else:
                        try:
                            chaos.fire("gen.session.kill_owner",
                                       replica=addr, session=sid)
                        except chaos.FaultInjected as e:
                            # the drill: the owner dies after producing
                            # this token but before the relay — it is
                            # lost upstream and a survivor must
                            # regenerate it
                            return resume_or_die(
                                f"owner {addr} killed (fault "
                                f"injection)", cause=e)
                        try:
                            self._ensure_stream_headers(
                                handler, sess, request_id, ctype)
                            self._relay_chunk(handler, line)
                        except OSError as e:
                            self._drop_conn(addr)
                            raise _StreamAborted(str(e)) from e
                        sess["tokens"].append(int(obj["token"]))
                        if resumed:
                            _profiler.runtime_metrics.inc(
                                "gen.session.spliced_tokens")
                        self.sessions.note(sid, delivered=delivered + 1)
                elif obj is not None and obj.get("done") \
                        and "migrate" in obj:
                    # drain-time hand-back: the owner checkpointed the
                    # stream at a token boundary — re-place it on a
                    # survivor (the owner is NOT down, just leaving)
                    return resume_or_die(
                        f"owner {addr} draining (migrate tail at "
                        f"token {obj['migrate'].get('resume_from')})",
                        mark_down=False)
                elif obj is not None and obj.get("done") \
                        and obj.get("error") is not None \
                        and obj.get("retryable"):
                    # the replica ended the stream with a RETRYABLE
                    # failure tail (scheduler abort, stall): resume
                    # on a sibling instead of surfacing it
                    return resume_or_die(
                        f"retryable upstream error tail "
                        f"({(obj.get('error') or {}).get('type')})",
                        mark_down=False)
                elif obj is not None and obj.get("done"):
                    # clean finish or non-retryable error: relay the
                    # tail verbatim and evict the session
                    try:
                        self._ensure_stream_headers(
                            handler, sess, request_id, ctype)
                        self._relay_chunk(handler, line)
                    except OSError as e:
                        self._drop_conn(addr)
                        raise _StreamAborted(str(e)) from e
                    terminal = ("upstream_error" if obj.get("error")
                                else "ok")
                    self.sessions.finish(sid)
                else:
                    # unparseable / unknown event shape: relay verbatim
                    try:
                        self._ensure_stream_headers(
                            handler, sess, request_id, ctype)
                        self._relay_chunk(handler, line)
                    except OSError as e:
                        self._drop_conn(addr)
                        raise _StreamAborted(str(e)) from e
                try:
                    chaos.fire("gen.stream.truncate", replica=addr,
                               session=sid)
                    line = resp.readline()
                except chaos.FaultInjected as e:
                    if terminal is not None:
                        self._drop_conn(addr)
                        line = b""
                    else:
                        return resume_or_die(
                            "stream truncated (fault injection)",
                            mark_down=False, cause=e)
                except (OSError, http.client.HTTPException) as e:
                    if terminal is not None:
                        self._drop_conn(addr)
                        line = b""
                    else:
                        return resume_or_die(
                            f"owner {addr} died mid-stream: {e}",
                            cause=e)
                if not line:
                    break
            if terminal is None:
                # EOF without a terminal tail: the owner closed the
                # stream mid-decode (hard kill between chunks)
                return resume_or_die(
                    f"owner {addr} closed the stream without a "
                    f"terminal event")
            try:
                handler.wfile.write(b"0\r\n\r\n")
                handler.wfile.flush()
            except OSError as e:
                raise _StreamAborted(str(e)) from e
            return terminal
        finally:
            with self._lock:
                entry = self._table.get(addr)
                if entry is not None:
                    entry["outstanding"] = max(
                        0, entry["outstanding"] - 1)

    def _relay_stream_verbatim(self, addr, handler, request_id, resp,
                               first, ctype):
        """The session-less relay (body did not parse as a generate
        request): chunks pass through verbatim, a mid-stream upstream
        death terminates with a legacy error tail — no resume."""
        import http.client
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Transfer-Encoding", "chunked")
            if request_id:
                handler.send_header("X-Request-Id", request_id)
            handler.end_headers()
            self._relay_chunk(handler, first)
        except OSError as e:
            self._drop_conn(addr)
            raise _StreamAborted(str(e)) from e
        last = first
        while True:
            try:
                line = resp.readline()
            except (OSError, http.client.HTTPException) as e:
                # upstream died MID-stream: the request cannot be
                # replayed (tokens already delivered) — terminate
                # with a structured error line the client can parse
                self._drop_conn(addr)
                self._mark_down(addr)
                self._finish_stream(handler, error=(
                    f"replica {addr} died mid-stream: {e}"))
                return "upstream_died"
            if not line:
                break
            last = line
            try:
                self._relay_chunk(handler, line)
            except OSError as e:
                self._drop_conn(addr)
                raise _StreamAborted(str(e)) from e
        try:
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except OSError as e:
            raise _StreamAborted(str(e)) from e
        # a replica-side failure (scheduler crash, stall) ends the
        # stream CLEANLY with an {"error": ..., "done": true} tail
        # — one JSON parse of the final line keeps that out of the
        # success metrics without re-encoding the relayed body
        if b'"error"' in last:
            try:
                if json.loads(last).get("error"):
                    return "upstream_error"
            except ValueError:
                pass
        return "ok"

    @staticmethod
    def _resume_body(sess, raw):
        """The request body for one attempt: the caller's bytes
        verbatim until a resume happens, then a rebuilt re-prefill
        request — the original prompt plus every token already
        delivered downstream, with ``resume_from`` so the survivor
        numbers its continuation exactly where the dead owner
        stopped."""
        if not sess["resumed"]:
            return raw
        delivered = sess["resume_from0"] + len(sess["tokens"])
        p = {"prompt": sess["prompt"] + sess["tokens"],
             "max_new_tokens": sess["max_new"] - len(sess["tokens"]),
             "resume_from": delivered,
             "stream": sess["stream"],
             "session_id": sess["sid"]}
        if sess["eos_id"] is not None:
            p["eos_id"] = sess["eos_id"]
        return json.dumps(p).encode()

    @staticmethod
    def _ensure_stream_headers(handler, sess, request_id, ctype):
        """Send the downstream 200 + chunked headers exactly once per
        CLIENT response, even when the upstream relay fails over
        mid-stream (the spliced continuation rides the same
        response)."""
        if sess["sent_headers"]:
            return
        handler.send_response(200)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Transfer-Encoding", "chunked")
        if request_id:
            handler.send_header("X-Request-Id", request_id)
        handler.end_headers()
        sess["sent_headers"] = True

    def _synthesize_done(self, handler, sess, reason="length"):
        """Every budgeted token reached the client but the owner died
        before its done tail: the router KNOWS the stream is complete,
        so it synthesizes the terminal event instead of burning a
        resume that would be rejected for an empty budget."""
        delivered = sess["resume_from0"] + len(sess["tokens"])
        line = (json.dumps({"done": True, "finish_reason": reason,
                            "tokens": delivered,
                            "token_index": delivered}) + "\n").encode()
        try:
            self._relay_chunk(handler, line)
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except OSError:
            handler.close_connection = True
        self.sessions.finish(sess["sid"])

    @staticmethod
    def _relay_chunk(handler, line):
        handler.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
        handler.wfile.flush()

    def _finish_stream(self, handler, error, etype="upstream_died",
                       token_index=None, retryable=True):
        """Terminate an already-started chunked relay with a structured
        error tail.  New tails carry the ``token_index`` high-water
        mark plus a top-level ``retryable`` flag so resuming clients
        know exactly where the stream stopped; legacy tails (neither
        field) must keep parsing — the protocol regression test holds
        both shapes against the schema."""
        obj = {"error": {"type": etype, "message": error},
               "done": True, "retryable": bool(retryable)}
        if token_index is not None:
            obj["token_index"] = int(token_index)
        try:
            line = (json.dumps(obj) + "\n").encode()
            self._relay_chunk(handler, line)
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except OSError:
            handler.close_connection = True

    @staticmethod
    def _shed(code, etype, message, tried, retry_after=None):
        obj = {"error": {"type": etype, "message": message},
               "retryable": True,
               "replicas_tried": list(tried)}
        headers = None
        if retry_after is not None:
            obj["retry_after_s"] = retry_after
            headers = {"Retry-After": f"{retry_after:.3f}"}
        return code, json.dumps(obj).encode(), "application/json", \
            headers

    def _pooled_conn(self, addr, timeout):
        """(reused, conn): this handler thread's keep-alive connection
        to ``addr``, or a fresh one.  The per-attempt timeout is applied
        to the live socket on reuse."""
        import http.client

        from paddle_tpu.fault.retry import parse_hostport
        pool = getattr(self._tl, "conns", None)
        if pool is None:
            pool = self._tl.conns = {}
        conn = pool.get(addr)
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return True, conn
        host, port = parse_hostport(addr)
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        pool[addr] = conn
        return False, conn

    def _drop_conn(self, addr):
        pool = getattr(self._tl, "conns", None)
        conn = pool.pop(addr, None) if pool else None
        if conn is not None:
            conn.close()

    def _forward(self, addr, path, raw, request_id, remaining):
        """One proxied attempt.  Success and PERMANENT upstream errors
        pass through verbatim; retryable upstream errors and transport
        failures raise (the policy fails the request over)."""
        import http.client

        from paddle_tpu.fault import chaos
        try:
            chaos.fire("fleet.route.blackhole", replica=addr)
        except chaos.FaultInjected as e:
            self._mark_down(addr)
            raise _Transient(f"route to {addr} blackholed") from e
        with self._lock:
            entry = self._table.get(addr)
            if entry is not None:
                entry["outstanding"] += 1
                entry["requests"] += 1
        timeout = min(remaining, self._attempt_timeout)
        headers = {
            "Content-Type": "application/json",
            "X-Request-Id": request_id,
            # the REMAINING budget, not the original: replicas bound
            # their batcher wait by what the caller has left
            "X-Deadline-Ms": str(int(remaining * 1000)),
        }
        try:
            for retry_fresh in (False, True):
                reused, conn = self._pooled_conn(addr, timeout)
                try:
                    conn.request("POST", path, raw, headers)
                    resp = conn.getresponse()
                    body = resp.read()
                    status = resp.status
                    if resp.will_close:
                        self._drop_conn(addr)
                    break
                except (OSError, http.client.HTTPException) as e:
                    self._drop_conn(addr)
                    if reused and not retry_fresh:
                        # a stale keep-alive connection (replica idled
                        # it out) must not read as replica death: one
                        # fresh-connection retry against the SAME
                        # replica before declaring it unreachable
                        continue
                    self._mark_down(addr)
                    raise ConnectionError(
                        f"replica {addr} unreachable: {e}") from e
        finally:
            with self._lock:
                entry = self._table.get(addr)
                if entry is not None:
                    entry["outstanding"] = max(
                        0, entry["outstanding"] - 1)
        if status == 200:
            return status, body, "application/json", None
        from paddle_tpu.fault.retry import parse_retry_after
        hint_raw = resp.getheader("Retry-After")
        try:
            parsed = json.loads(body)
        except ValueError:
            parsed = {"retryable": status in (429, 502, 503, 504)}
        if parsed.get("retryable"):
            if status == 429 and \
                    not self._alternative_with_headroom(addr):
                # saturated replica, no sibling with scraped headroom:
                # pass the 429 + Retry-After through VERBATIM so the
                # client backs off instead of the router burning its
                # budget hammering a uniformly saturated fleet
                return status, body, "application/json", \
                    ({"Retry-After": hint_raw} if hint_raw else None)
            err = parsed.get("error") or {}
            exc = _Transient(
                f"replica {addr} replied {status} "
                f"{err.get('type', 'retryable')}: "
                f"{err.get('message', '')}")
            hint = parse_retry_after(hint_raw)
            if hint is not None:
                # the retry policy paces the failover by the replica's
                # own hint instead of its default backoff
                exc.retry_after = hint
            raise exc
        # permanent upstream error (400 bad feed, 500 model bug): the
        # caller must see it unchanged — failing over would just repeat
        # the same error on a healthy replica
        return status, body, "application/json", None

    # -- lifecycle ---------------------------------------------------------
    def start_background(self):
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True, name="fleet-router")
        t.start()
        return t

    def serve_forever(self):
        self._server.serve_forever()

    def shutdown(self):
        self._stop.set()
        if self._slo is not None:
            self._slo.stop()
        self._server.shutdown()
        self._server.server_close()
        if self._master is not None:
            self._master.close()
