"""Fleet replica: an :class:`~paddle_tpu.serving.InferenceServer`
enrolled in master-backed service discovery.

The reference framework's production unit was a *cluster* — trainers
and pservers coordinated by the Go master's leases and heartbeats.
:class:`FleetReplica` re-aims that machinery at inference: on startup
(once `/readyz` would pass, i.e. loaded AND warmed) the replica
registers its address with the master under a TTL lease and renews it
from a heartbeat thread; a replica that stops renewing — crash, hang,
partition — simply vanishes from :meth:`MasterService.list_replicas`
and the router stops sending it traffic.  No prober, no gossip: a
silent replica IS a dead replica.

Lease loss while alive (master restarted, `master.lease.expire` drill)
flips the wrapped server's ``lease_state`` so `/readyz` answers
``503 lease_lost`` — the load balancer and the router agree about
health — and, with ``auto_rejoin``, the next heartbeat re-registers.

The ``fleet.replica.kill`` failpoint fires in the heartbeat loop: armed
with ``kill`` (subprocess drills) it is a real ``os._exit(137)``;
armed with ``error`` (in-process drills) it routes to :meth:`kill`,
the abrupt no-drain stop that chaos tests use to hard-kill one replica
of an in-process fleet mid-load.
"""

from __future__ import annotations

import logging
import os
import threading

from paddle_tpu.serving import InferenceServer

logger = logging.getLogger(__name__)

__all__ = ["FleetReplica"]


class FleetReplica:
    """One serving replica of a master-routed fleet.

    ``server_kwargs`` pass through to :class:`InferenceServer` —
    ``warmup=True`` plus a persistent compile cache
    (``PADDLE_TPU_COMPILE_CACHE``) is the fast-scale-out configuration:
    a replacement replica AOT-compiles from the cache before `/readyz`
    flips, so rolling restarts never serve a cold compile.
    """

    def __init__(self, model_dir, master_addr, replica_id=None,
                 host="127.0.0.1", port=0, lease_ttl=5.0,
                 heartbeat_interval=None, advertise_host=None,
                 auto_rejoin=True, **server_kwargs):
        from paddle_tpu.parallel.master import MasterClient
        self.replica_id = replica_id or \
            f"replica-{os.getpid():x}-{os.urandom(3).hex()}"
        self.lease_ttl = float(lease_ttl)
        # 3 renews per TTL: one lost heartbeat never expires the lease
        self.heartbeat_interval = float(
            heartbeat_interval if heartbeat_interval is not None
            else max(0.05, self.lease_ttl / 3.0))
        self.server = InferenceServer(model_dir, host=host, port=port,
                                      **server_kwargs)
        self.addr = self.server.addr
        self.advertise_addr = \
            f"{advertise_host or self.addr[0]}:{self.addr[1]}"
        self.auto_rejoin = bool(auto_rejoin)
        self._master = MasterClient(master_addr)
        self._stop = threading.Event()
        # serializes lease mutations (register vs drain's deregister):
        # a rejoin racing drain() must never re-enroll a dead listener
        self._lease_lock = threading.Lock()
        self._hb_thread = None
        self._serve_thread = None
        self.killed = False
        self._epoch = None

    # -- lifecycle ---------------------------------------------------------
    def warm(self, ready_timeout=300.0):
        """Serve and wait for readiness (load + warmup) WITHOUT
        registering: the warm-standby half of :meth:`start`.

        A warmed replica has paid its model load and AOT warmup — with
        ``PADDLE_TPU_COMPILE_CACHE`` set, through the persistent
        compile cache — but takes no traffic: the router never
        discovers it until :meth:`enroll` registers the lease.  This is
        the fleet controller's standby pool shape: scale-up becomes a
        registration (milliseconds), not a compile (minutes).

        Also labels this process's timeline row for merged fleet traces
        (``obs.trace.set_process_name``; first caller wins, so an
        operator-chosen name is never overwritten).  Raises if the
        model load failed — and a failed warm tears down what it
        already built (listener, master connection), so the caller is
        not left with a leaked port it has no handle to drain.
        Idempotent once warmed."""
        from paddle_tpu.obs import trace as _trace
        if self._serve_thread is not None:
            return self
        _trace.set_process_name(f"replica:{self.replica_id}")
        self._serve_thread = self.server.start_background()
        try:
            if not self.server.wait_until_ready(ready_timeout):
                raise TimeoutError(
                    f"replica {self.replica_id} not ready in "
                    f"{ready_timeout}s")
        except BaseException:
            self._stop.set()
            try:
                self.server.shutdown()
            except Exception:
                pass
            try:
                self._master.close()
            except Exception:
                pass
            raise
        return self

    def enroll(self):
        """Register a WARMED replica with the master and start the
        heartbeat thread — the promotion half of :meth:`start`, and the
        fleet controller's scale-up primitive.  Registration is
        deliberately after readiness: the router must never discover a
        replica whose `/readyz` would still say 503.  Raises
        ``RuntimeError`` when called before :meth:`warm`; idempotent
        once enrolled."""
        if self._serve_thread is None:
            raise RuntimeError(
                f"replica {self.replica_id} not warmed: call warm() "
                f"before enroll()")
        if self._hb_thread is not None:
            return self
        try:
            self._register()
        except BaseException:
            self._stop.set()
            try:
                self.server.shutdown()
            except Exception:
                pass
            try:
                self._master.close()
            except Exception:
                pass
            raise
        self._hb_thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name=f"fleet-hb-{self.replica_id}")
        self._hb_thread.start()
        return self

    def start(self, ready_timeout=300.0):
        """Serve, wait for readiness (load + warmup), THEN register:
        :meth:`warm` + :meth:`enroll`."""
        self.warm(ready_timeout)
        self.enroll()
        return self

    def _register(self):
        from paddle_tpu import profiler as _profiler
        with self._lease_lock:
            if self._stop.is_set():
                # drain()/kill() won the race: stay deregistered
                return
            lease = self._master.register_replica(
                self.replica_id, self.advertise_addr, ttl=self.lease_ttl,
                meta={"pid": os.getpid()})
            self._epoch = lease["epoch"]
            self.server.lease_state = "held"
        _profiler.runtime_metrics.inc("fleet.replica_registrations")

    def _beat_loop(self):
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault import chaos
        while not self._stop.wait(self.heartbeat_interval):
            try:
                # armed `kill`: a real os._exit mid-load (subprocess
                # drill); armed `error`: the in-process hard-kill below
                chaos.fire("fleet.replica.kill",
                           replica_id=self.replica_id)
            except chaos.FaultInjected:
                logger.warning("fleet.replica.kill fired: hard-killing "
                               "replica %s", self.replica_id)
                self.kill()
                return
            try:
                renewed = self._master.renew_replica(self.replica_id,
                                                     epoch=self._epoch)
            except Exception:
                # transport failures were already retried by the client
                # policy; keep beating — the lease may outlive the blip
                continue
            if renewed:
                if self.server.lease_state != "held":
                    self.server.lease_state = "held"
                continue
            # lease lost while alive: surface it on /readyz first, then
            # (optionally) re-enroll — the order matters, a probe racing
            # the rejoin must never see "ready" without a lease
            if self.server.lease_state != "lost":
                self.server.lease_state = "lost"
                _profiler.runtime_metrics.inc("fleet.lease_lost")
                logger.warning("replica %s lost its fleet lease",
                               self.replica_id)
            if self.auto_rejoin and not self._stop.is_set():
                # (_stop re-checked: drain() deregisters AFTER setting
                # the flag — a rejoin racing it would resurrect a dead
                # replica in the routing table for a full TTL)
                try:
                    live = {r["id"] for r in self._master.list_replicas()}
                    if self.replica_id in live:
                        # a NEWER incarnation holds this id (rolling
                        # restart with a stable --replica-id): stand
                        # down instead of fighting over the lease —
                        # re-registering here would epoch-bump the
                        # replacement out and ping-pong forever
                        continue
                    self._register()
                except Exception:
                    pass  # master still down: retry next beat

    # -- exits -------------------------------------------------------------
    def drain(self, deadline_s=30.0):
        """Rolling-restart drain: deregister (the router stops routing
        new requests), stop heartbeats, checkpoint-migrate any active
        generative sessions, then shut the server down — stop
        accepting, finish in-flight, release resources.  The lease is
        released *before* the listener closes, so the fleet's ready
        count drops by exactly one with no refused-connection window.

        ``deadline_s`` bounds how long in-flight generative streams may
        run to natural completion; on expiry the remaining sessions are
        checkpointed at a token boundary and handed back (as ``migrate``
        tails on their still-open streams) for re-placement on a
        survivor, instead of being awaited forever.  Returns the list
        of migrated session checkpoints (empty for non-gen bundles)."""
        self._stop.set()
        with self._lease_lock:
            # under the lock: an in-flight rejoin either registered
            # BEFORE this deregister (undone here) or observes _stop
            # and stands down — no window re-enrolls a dead listener
            try:
                self._master.deregister_replica(self.replica_id)
            except Exception:
                pass  # master gone: the lease TTL expires it anyway
            self.server.lease_state = None
        # checkpoint BEFORE the listener closes: migrate tails must
        # flush on the streams' still-open connections
        checkpoints = []
        try:
            checkpoints = self.server.drain_sessions(deadline_s)
        except Exception:
            logger.exception("replica %s: session drain failed",
                             self.replica_id)
        self.server.shutdown()
        self._master.close()
        return checkpoints

    def kill(self):
        """In-process hard-kill: stop heartbeats and close the listener
        with NO drain and NO deregistration — in-flight connections race
        the close, new connections are refused, and the master only
        notices when the lease TTL runs out.  This is the in-process
        analog of ``kill -9`` for chaos drills (subprocess drills arm
        ``fleet.replica.kill=kill`` for the real thing)."""
        self.killed = True
        self._stop.set()
        try:
            # sever active generative streams too: closing the listener
            # alone leaves handler threads decoding — a real SIGKILL
            # kills them, so the in-process analog must as well (their
            # clients see a retryable error tail and resume elsewhere)
            self.server.abort_streams()
        except Exception:
            pass
        try:
            self.server._server.shutdown()
        except Exception:
            pass
        try:
            self.server._server.server_close()
        except Exception:
            pass
        try:
            self._master.close()
        except Exception:
            pass

    def close(self):
        """Alias for :meth:`drain` (context-manager friendliness)."""
        self.drain()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if not self.killed:
            self.drain()
        return False
