"""Multi-replica serving fleet: master-backed discovery, health-aware
routing, and chaos-proof failover.

The reference framework's production story is a *cluster* — trainers
and pservers coordinated by a Go master with leases and fault
tolerance (``go/master/service.go``).  This package re-aims that
machinery at inference:

- :class:`~paddle_tpu.fleet.replica.FleetReplica` — an
  :class:`~paddle_tpu.serving.InferenceServer` that registers with the
  master on readiness and renews a TTL lease via heartbeat; an expired
  lease = unhealthy, dropped from the routing table, and `/readyz`
  answers ``503 lease_lost`` while the process is alive.
- :class:`~paddle_tpu.fleet.router.FleetRouter` — a thin front-end
  that discovers live replicas from the master, spreads traffic by
  least-outstanding requests, and retries failed attempts on a
  *different* replica under a full-jitter
  :class:`~paddle_tpu.fault.RetryPolicy`, bounded end to end by the
  caller's ``X-Deadline-Ms`` budget; ``X-Request-Id`` makes one
  request traceable across replicas.
- the client-side alternative: ``ServingClient(master=...)`` (or a
  list of addresses) balances and fails over without a router hop.
- :class:`~paddle_tpu.fleet.controller.FleetController` — the closed
  control loop: SLO pressure and scraper rollups in, scale-up from a
  warm-standby pool / idle drain / admission-control backpressure
  (429 + Retry-After via the router's degradation ladder) out.
- :class:`~paddle_tpu.fleet.traffic.TrafficReplay` — the load side:
  open-loop traffic replay (diurnal ramps, flash crowds, heavy-tailed
  prompt mixes) that drills the control loop under chaos.

See ``docs/serving_fleet.md`` for topology, failover semantics, the
rolling-restart runbook, the autoscaling/backpressure runbook, and
the chaos drills.
"""

from __future__ import annotations

from paddle_tpu.fleet.controller import ControllerPolicy, \
    FleetController, load_policy
from paddle_tpu.fleet.replica import FleetReplica
from paddle_tpu.fleet.router import FleetRouter
from paddle_tpu.fleet.sessions import SessionTable, new_session_id, \
    validate_checkpoint, validate_stream_event
from paddle_tpu.fleet.traffic import TrafficReplay

__all__ = ["FleetReplica", "FleetRouter", "FleetController",
           "ControllerPolicy", "load_policy", "SessionTable",
           "TrafficReplay", "new_session_id", "validate_checkpoint",
           "validate_stream_event"]
