"""Generative session registry: the state that makes a ``/generate``
stream survive its replica.

The reference framework's Go master keeps a lease table so a dead
trainer's task can be re-assigned without losing the pass
(``go/master/service.go``); this module keeps the serving-plane
analog: one bounded table of live generative sessions — which replica
owns the stream, a hash of the prompt, and how many tokens the client
has already received — so the :class:`~paddle_tpu.fleet.router.
FleetRouter` can (a) route a follow-up or resume request back to the
owning replica and (b) re-prefill ``prompt + tokens_so_far`` on a
survivor when the owner dies mid-stream.  Greedy decode is
deterministic (the KV-exactness tests are the proof obligation), so
the re-prefilled continuation is token-identical and the router can
splice the two streams into one duplicate-free sequence keyed on the
monotone ``token_index`` every streamed event carries.

Entries are evicted on ``done``; the table is bounded, and evicting a
session that never finished counts ``gen.session.orphaned`` — the
leak detector for streams whose client vanished without a terminal
event.

The module also owns the resume-protocol schema validators
(:func:`validate_stream_event`, :func:`validate_checkpoint`) that the
``paddle_tpu selfcheck`` ``sessions`` section round-trips — protocol
drift fails a release gate, not a production resume.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

__all__ = ["SessionTable", "new_session_id", "prompt_hash",
           "validate_stream_event", "validate_checkpoint"]


def new_session_id():
    """Mint a session id (uuid-free: 12 hex bytes of os.urandom)."""
    return f"s-{os.urandom(12).hex()}"


def prompt_hash(prompt):
    """Stable short hash of a token-id prompt (session-table identity
    check: a resume whose prompt prefix changed is a different
    request, not a resume)."""
    h = hashlib.sha256()
    for t in prompt:
        h.update(str(int(t)).encode())
        h.update(b",")
    return h.hexdigest()[:16]


class SessionTable:
    """Bounded, thread-safe registry of live generative sessions.

    Each entry tracks the owning replica, the prompt hash, and the
    count of tokens DELIVERED to the client so far (the resume
    index).  ``finish`` evicts on ``done``; capacity overflow evicts
    the least-recently-touched entry and counts it as orphaned when it
    never finished.
    """

    def __init__(self, capacity=1024):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._table = {}        # sid -> entry dict (insertion ordered)
        self.orphaned = 0       # non-done entries evicted by capacity

    def __len__(self):
        with self._lock:
            return len(self._table)

    def begin(self, sid, replica, prompt, max_new_tokens,
              delivered=0):
        """Register (or re-register, on resume) a session. Returns the
        entry."""
        now = time.monotonic()
        with self._lock:
            entry = self._table.pop(sid, None)
            if entry is None:
                entry = {"sid": sid, "created_t": now}
            entry.update(replica=replica,
                         prompt_hash=prompt_hash(prompt),
                         prompt_len=len(prompt),
                         max_new_tokens=int(max_new_tokens),
                         delivered=int(delivered),
                         done=False, touched_t=now)
            self._table[sid] = entry        # re-insert: LRU order
            self._evict_over_capacity()
            return entry

    def note(self, sid, replica=None, delivered=None):
        """Update a live session's owner and/or delivered count."""
        with self._lock:
            entry = self._table.get(sid)
            if entry is None:
                return None
            if replica is not None:
                entry["replica"] = replica
            if delivered is not None:
                entry["delivered"] = int(delivered)
            entry["touched_t"] = time.monotonic()
            return entry

    def lookup(self, sid):
        with self._lock:
            entry = self._table.get(sid)
            return dict(entry) if entry is not None else None

    def owner(self, sid):
        """The owning replica address, or None."""
        with self._lock:
            entry = self._table.get(sid)
            return entry["replica"] if entry is not None else None

    def finish(self, sid):
        """Terminal event delivered: evict the entry (returns it, or
        None when unknown)."""
        with self._lock:
            entry = self._table.pop(sid, None)
            if entry is not None:
                entry["done"] = True
            return entry

    def _evict_over_capacity(self):
        # caller holds the lock; dicts iterate in insertion order and
        # begin()/touch re-inserts, so the first key is the LRU entry
        from paddle_tpu import profiler as _profiler
        while len(self._table) > self.capacity:
            sid = next(iter(self._table))
            entry = self._table.pop(sid)
            if not entry.get("done"):
                self.orphaned += 1
                _profiler.runtime_metrics.inc("gen.session.orphaned")

    def snapshot(self):
        """The ``/stats`` body: counts plus a bounded sample of live
        sessions."""
        with self._lock:
            sample = [
                {"sid": e["sid"], "replica": e["replica"],
                 "delivered": e["delivered"],
                 "prompt_len": e["prompt_len"],
                 "age_s": round(time.monotonic() - e["created_t"], 3)}
                for e in list(self._table.values())[:32]]
            return {"count": len(self._table),
                    "capacity": self.capacity,
                    "orphaned": self.orphaned,
                    "sessions": sample}


# ---------------------------------------------------------------------------
# resume-protocol schemas (selfcheck `sessions` section round-trips these)
# ---------------------------------------------------------------------------

def _is_int(v):
    return isinstance(v, int) and not isinstance(v, bool)


def validate_stream_event(obj):
    """Problems with one streamed ``/generate`` ndjson event (empty =
    valid).  Three shapes are legal:

    - token:     ``{"token": id, "index": i}`` — ``index`` is the
      monotone token_index the dedupe/splice logic keys on;
    - terminal:  ``{"done": true, ...}`` with either
      ``finish_reason`` (clean), ``error`` (failure; new tails add
      ``token_index`` + top-level ``retryable``), or ``migrate``
      (drain-time hand-back: ``{"resume_from": i}``);
    - legacy terminal error tails WITHOUT ``token_index``/
      ``retryable`` still validate — old clients and old tails must
      keep parsing.
    """
    problems = []
    if not isinstance(obj, dict):
        return [f"event must be an object, got {type(obj).__name__}"]
    if "token" in obj:
        if not _is_int(obj["token"]):
            problems.append("token must be an int token id")
        if not _is_int(obj.get("index", None)) or obj.get("index", -1) < 0:
            problems.append("token event needs a non-negative int index")
        if obj.get("done"):
            problems.append("token event cannot also be terminal")
        return problems
    if not obj.get("done"):
        return ["non-token event must be terminal (done: true)"]
    kinds = [k for k in ("finish_reason", "error", "migrate") if k in obj]
    if len(kinds) != 1:
        problems.append("terminal event needs exactly one of "
                        "finish_reason / error / migrate, got "
                        f"{kinds or 'none'}")
        return problems
    if "finish_reason" in obj and not isinstance(obj["finish_reason"],
                                                 str):
        problems.append("finish_reason must be a string")
    if "error" in obj:
        err = obj["error"]
        if not isinstance(err, dict) or not isinstance(
                err.get("type"), str):
            problems.append("error must be an object with a type string")
        # token_index / retryable are OPTIONAL (legacy tails) but must
        # be well-typed when present
        if "token_index" in obj and (
                not _is_int(obj["token_index"]) or obj["token_index"] < 0):
            problems.append("token_index must be a non-negative int")
        if "retryable" in obj and not isinstance(obj["retryable"], bool):
            problems.append("retryable must be a boolean")
    if "migrate" in obj:
        mig = obj["migrate"]
        if not isinstance(mig, dict) or not _is_int(
                mig.get("resume_from", None)) or mig["resume_from"] < 0:
            problems.append("migrate must be an object with a "
                            "non-negative int resume_from")
        if obj.get("retryable") is not True:
            problems.append("migrate tails must be retryable: true "
                            "(the whole point is a resume)")
    return problems


def validate_checkpoint(ckpt):
    """Problems with a drain-time session checkpoint (empty = valid):
    the scheduler's token-boundary hand-back — prompt as submitted,
    tokens emitted since, the remaining budget, and the eos override —
    everything a survivor needs to continue token-identically."""
    problems = []
    if not isinstance(ckpt, dict):
        return [f"checkpoint must be an object, "
                f"got {type(ckpt).__name__}"]
    prompt = ckpt.get("prompt")
    if not isinstance(prompt, list) or not prompt or \
            not all(_is_int(t) for t in prompt):
        problems.append("prompt must be a non-empty list of int "
                        "token ids")
    tokens = ckpt.get("tokens")
    if not isinstance(tokens, list) or \
            not all(_is_int(t) for t in tokens):
        problems.append("tokens must be a list of int token ids")
    rem = ckpt.get("remaining_tokens")
    if not _is_int(rem) or rem < 0:
        problems.append("remaining_tokens must be a non-negative int")
    if "eos_id" in ckpt and ckpt["eos_id"] is not None and \
            not _is_int(ckpt["eos_id"]):
        problems.append("eos_id must be an int or null")
    if not isinstance(ckpt.get("reason"), str) or not ckpt.get("reason"):
        problems.append("reason must be a non-empty string")
    return problems
