"""Closed-loop fleet controller: SLO pressure in, scaling and
admission-control actions out.

PR 10's :class:`~paddle_tpu.obs.slo.SLOWatchdog` *detects* (breach log,
post-mortems) and PR 12's :class:`~paddle_tpu.obs.aggregate.FleetScraper`
*observes* (per-replica RPS/MFU/HBM rollups); this module closes the
loop — the missing "act" half of the reference framework's
fault-tolerant-cluster story.  One :class:`FleetController` per fleet
runs a periodic reconcile tick:

1. **Sense** — one federation scrape (demand = the scraper's counter-
   delta RPS), one watchdog evaluation (pressure = the worst
   value-vs-threshold margin across objectives, a *continuous* signal
   available BEFORE the binary breach fires).
2. **Degrade** — map pressure onto a graceful-degradation ladder:
   :meth:`FleetRouter.set_admission` sheds a growing fraction of
   arrivals with ``429`` + ``Retry-After`` (clamped to each caller's
   ``X-Deadline-Ms``) instead of queueing them into a timeout.  The
   ladder climbs one rung per pressured tick but descends only after
   ``recover_ticks`` consecutive healthy ticks — hysteresis, so a
   p99 hovering at the threshold cannot flap the fleet.
3. **Scale up** — on sustained pressure, promote a replica from the
   warm-standby pool: standbys are :meth:`FleetReplica.warm`-ed ahead
   of time (through the persistent XLA compile cache when
   ``PADDLE_TPU_COMPILE_CACHE`` is set), so scale-up is an
   :meth:`FleetReplica.enroll` — a lease registration, not a compile.
4. **Scale down** — on sustained idleness, drain the most recently
   promoted replica via the rolling-restart
   :meth:`FleetReplica.drain` path (finish in-flight, then leave).
5. **Replenish** — keep the standby pool at its target size with a
   background warm thread.

Placement stays in the router (least-outstanding with an HBM-headroom
tie-break from the same scrapes); the controller only changes how many
replicas there are and how many requests get in the door.

The policy is a small JSON document mirroring the SLO-spec pattern
(``PADDLE_TPU_AUTOSCALE=/path/policy.json`` arms it for the CLI;
``paddle_tpu selfcheck`` validates the schema statically).

Failpoints (chaos drills, registry in ``docs/fault_tolerance.md``):
``fleet.scale.stall`` fires per scale-up decision (armed ``error``:
the promotion is lost this tick — the drill for an exhausted machine
pool); ``fleet.standby.fail`` fires per standby warm attempt (armed
``error``: the warm fails and is retried next tick — the drill for a
standby host that dies mid-provision).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from paddle_tpu.obs.trace import span as _span

logger = logging.getLogger(__name__)

__all__ = ["FleetController", "ControllerPolicy", "load_policy",
           "validate_policy", "policy_from_env", "POLICY_ENV",
           "EXAMPLE_POLICY"]

POLICY_ENV = "PADDLE_TPU_AUTOSCALE"
POLICY_VERSION = 1

# the documented policy shape — selfcheck validates this constant so
# the schema validator itself is exercised even when no policy is armed
EXAMPLE_POLICY = {
    "version": 1,
    "interval_seconds": 1.0,
    "min_replicas": 1,
    "max_replicas": 4,
    "standby_pool": 1,
    "ready_timeout_seconds": 300.0,
    "scale_up": {
        "pressure_ratio": 0.8,
        "sustained_ticks": 2,
        "cooldown_seconds": 10.0,
    },
    "scale_down": {
        "idle_rps_per_replica": 0.5,
        "sustained_ticks": 10,
        "cooldown_seconds": 30.0,
    },
    "degrade": {
        "ladder": [0.0, 0.25, 0.5, 0.75],
        "engage_ratio": 0.95,
        "recover_ticks": 3,
        "retry_after_seconds": 1.0,
    },
}


def _is_number(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v == v and abs(v) != float("inf")


def _is_count(v, minimum=0):
    return isinstance(v, int) and not isinstance(v, bool) and v >= minimum


def validate_policy(obj):
    """Schema problems of a controller policy dict, as a list of
    strings (empty = valid).  Never raises — selfcheck renders the
    list, mirroring :func:`paddle_tpu.obs.slo.validate_spec`."""
    problems = []
    if not isinstance(obj, dict):
        return [f"policy must be a JSON object, "
                f"got {type(obj).__name__}"]
    if obj.get("version") != POLICY_VERSION:
        problems.append(f"version must be {POLICY_VERSION}, "
                        f"got {obj.get('version')!r}")
    for key in ("interval_seconds", "ready_timeout_seconds"):
        if key in obj and (not _is_number(obj[key]) or obj[key] <= 0):
            problems.append(f"{key} must be a positive number")
    for key in ("min_replicas", "max_replicas", "standby_pool"):
        if key in obj and not _is_count(
                obj[key], minimum=0 if key == "standby_pool" else 1):
            problems.append(
                f"{key} must be an integer >= "
                f"{0 if key == 'standby_pool' else 1}")
    lo = obj.get("min_replicas", 1)
    hi = obj.get("max_replicas", 4)
    if _is_count(lo, 1) and _is_count(hi, 1) and lo > hi:
        problems.append("min_replicas must be <= max_replicas")

    up = obj.get("scale_up", {})
    if not isinstance(up, dict):
        problems.append("scale_up must be an object")
        up = {}
    if "pressure_ratio" in up and (
            not _is_number(up["pressure_ratio"])
            or up["pressure_ratio"] <= 0):
        problems.append("scale_up.pressure_ratio must be > 0")
    if "sustained_ticks" in up and not _is_count(up["sustained_ticks"], 1):
        problems.append("scale_up.sustained_ticks must be an "
                        "integer >= 1")
    if "cooldown_seconds" in up and (
            not _is_number(up["cooldown_seconds"])
            or up["cooldown_seconds"] < 0):
        problems.append("scale_up.cooldown_seconds must be >= 0")

    down = obj.get("scale_down", {})
    if not isinstance(down, dict):
        problems.append("scale_down must be an object")
        down = {}
    if "idle_rps_per_replica" in down and (
            not _is_number(down["idle_rps_per_replica"])
            or down["idle_rps_per_replica"] < 0):
        problems.append("scale_down.idle_rps_per_replica must be >= 0")
    if "sustained_ticks" in down and \
            not _is_count(down["sustained_ticks"], 1):
        problems.append("scale_down.sustained_ticks must be an "
                        "integer >= 1")
    if "cooldown_seconds" in down and (
            not _is_number(down["cooldown_seconds"])
            or down["cooldown_seconds"] < 0):
        problems.append("scale_down.cooldown_seconds must be >= 0")

    deg = obj.get("degrade", {})
    if not isinstance(deg, dict):
        problems.append("degrade must be an object")
        deg = {}
    ladder = deg.get("ladder")
    if ladder is not None:
        if not isinstance(ladder, list) or not ladder or \
                not all(_is_number(f) and 0 <= f <= 1 for f in ladder):
            problems.append("degrade.ladder must be a non-empty list of "
                            "shed fractions in [0, 1]")
        elif ladder[0] != 0:
            problems.append("degrade.ladder[0] must be 0 (level 0 "
                            "admits everything)")
        elif any(b < a for a, b in zip(ladder, ladder[1:])):
            problems.append("degrade.ladder must be non-decreasing")
    if "engage_ratio" in deg and (
            not _is_number(deg["engage_ratio"])
            or deg["engage_ratio"] <= 0):
        problems.append("degrade.engage_ratio must be > 0")
    if "recover_ticks" in deg and not _is_count(deg["recover_ticks"], 1):
        problems.append("degrade.recover_ticks must be an integer >= 1")
    if "retry_after_seconds" in deg and (
            not _is_number(deg["retry_after_seconds"])
            or deg["retry_after_seconds"] < 0):
        problems.append("degrade.retry_after_seconds must be >= 0")

    known = {"version", "interval_seconds", "min_replicas",
             "max_replicas", "standby_pool", "ready_timeout_seconds",
             "scale_up", "scale_down", "degrade", "description"}
    unknown = set(obj) - known
    if unknown:
        problems.append(f"unknown keys {sorted(unknown)}")
    for section, keys in (
            ("scale_up", {"pressure_ratio", "sustained_ticks",
                          "cooldown_seconds"}),
            ("scale_down", {"idle_rps_per_replica", "sustained_ticks",
                            "cooldown_seconds"}),
            ("degrade", {"ladder", "engage_ratio", "recover_ticks",
                         "retry_after_seconds"})):
        sec = obj.get(section)
        if isinstance(sec, dict):
            unknown = set(sec) - keys
            if unknown:
                problems.append(f"{section}: unknown keys "
                                f"{sorted(unknown)}")
    return problems


class ControllerPolicy:
    """A validated controller policy; construct via
    :func:`load_policy`.  Missing knobs take :data:`EXAMPLE_POLICY`'s
    defaults, so a policy file only states what it changes."""

    def __init__(self, obj, source=None):
        problems = validate_policy(obj)
        if problems:
            raise ValueError(
                "invalid controller policy"
                + (f" ({source})" if source else "") + ":\n  "
                + "\n  ".join(problems))
        self.source = source
        self.interval = float(obj.get(
            "interval_seconds", EXAMPLE_POLICY["interval_seconds"]))
        self.min_replicas = int(obj.get(
            "min_replicas", EXAMPLE_POLICY["min_replicas"]))
        self.max_replicas = int(obj.get(
            "max_replicas", EXAMPLE_POLICY["max_replicas"]))
        self.standby_pool = int(obj.get(
            "standby_pool", EXAMPLE_POLICY["standby_pool"]))
        self.ready_timeout = float(obj.get(
            "ready_timeout_seconds",
            EXAMPLE_POLICY["ready_timeout_seconds"]))
        self.scale_up = dict(EXAMPLE_POLICY["scale_up"],
                             **(obj.get("scale_up") or {}))
        self.scale_down = dict(EXAMPLE_POLICY["scale_down"],
                               **(obj.get("scale_down") or {}))
        self.degrade = dict(EXAMPLE_POLICY["degrade"],
                            **(obj.get("degrade") or {}))

    def to_dict(self):
        return {"version": POLICY_VERSION,
                "interval_seconds": self.interval,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "standby_pool": self.standby_pool,
                "ready_timeout_seconds": self.ready_timeout,
                "scale_up": dict(self.scale_up),
                "scale_down": dict(self.scale_down),
                "degrade": dict(self.degrade)}


def load_policy(policy):
    """Coerce a path / dict / ControllerPolicy into a
    :class:`ControllerPolicy`; raises ``ValueError`` naming every
    schema problem."""
    if isinstance(policy, ControllerPolicy):
        return policy
    if isinstance(policy, dict):
        return ControllerPolicy(policy)
    with open(policy) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            raise ValueError(f"invalid controller policy ({policy}): "
                             f"not JSON: {e}")
    return ControllerPolicy(obj, source=str(policy))


def policy_from_env():
    """A :class:`ControllerPolicy` from ``PADDLE_TPU_AUTOSCALE``, or
    None when the env var is unset.  A malformed file WARNS and
    disarms (selfcheck is the static gate that fails it loudly)."""
    path = os.environ.get(POLICY_ENV, "").strip()
    if not path:
        return None
    try:
        return load_policy(path)
    except (OSError, ValueError) as e:
        import warnings
        warnings.warn(f"{POLICY_ENV}={path!r} did not load — fleet "
                      f"controller policy disarmed: {e}")
        return None


class FleetController:
    """The reconcile loop over one :class:`FleetRouter`'s fleet.

    ``standby_factory`` is a zero-argument callable returning an
    UNSTARTED :class:`~paddle_tpu.fleet.replica.FleetReplica`; the
    controller warms it into the standby pool and enrolls it on
    scale-up.  Without a factory the controller still runs the
    degradation ladder and scale-DOWN of replicas it owns — it just
    cannot add capacity.

    ``watchdog`` defaults to the router's own SLO watchdog; the
    controller drives :meth:`SLOWatchdog.maybe_evaluate` from its tick
    (interval-gated, so sharing the watchdog with the router's
    background thread never double-evaluates a window).

    Thread-safety: the public surface (:meth:`tick`, :meth:`state`,
    :meth:`prewarm`, :meth:`shutdown`) may be called from any thread;
    replica promotion/drain happen outside the controller lock so a
    slow drain can never block ``state()`` probes.
    """

    def __init__(self, router, policy=None, standby_factory=None,
                 watchdog=None, metrics=None):
        if policy is None:
            policy = policy_from_env()
        self.policy = load_policy(policy) if policy is not None \
            else ControllerPolicy(dict(EXAMPLE_POLICY))
        self.router = router
        self._standby_factory = standby_factory
        self._watchdog = watchdog if watchdog is not None \
            else getattr(router, "_slo", None)
        if metrics is None:
            from paddle_tpu.profiler import runtime_metrics
            metrics = runtime_metrics
        self._metrics = metrics
        self._lock = threading.Lock()
        self._standbys = []       # warmed, NOT enrolled
        self._owned = []          # enrolled by this controller (LIFO)
        self._warming = False     # one background warm at a time
        self._level = 0           # current degradation rung
        self._healthy_ticks = 0   # consecutive ticks below engage_ratio
        self._pressure_ticks = 0  # consecutive ticks above pressure_ratio
        self._idle_ticks = 0      # consecutive idle-rate ticks
        self._last_scale_up = float("-inf")
        self._last_scale_down = float("-inf")
        self.last_pressure = 0.0
        self.last_rps = None
        self._stop = threading.Event()
        self._thread = None

    # -- sensing -----------------------------------------------------------
    def _pressure(self, values):
        """The worst value-vs-threshold margin across the watchdog's
        last pass, normalized so 1.0 = at the threshold and >1 =
        breaching.  ``max``-style objectives (quantile, error_rate)
        contribute ``value / threshold``; ``rate_floor`` contributes
        ``threshold / value`` (a rate at half its floor reads 2.0).
        Windows with nothing to judge contribute nothing."""
        worst = 0.0
        for v in values or []:
            value, threshold = v.get("value"), v.get("threshold")
            if value is None or threshold is None:
                continue
            if v.get("kind") == "rate_floor":
                ratio = float("inf") if value <= 0 \
                    else threshold / value
            else:
                ratio = (float("inf") if threshold <= 0 and value > 0
                         else (value / threshold if threshold > 0
                               else 0.0))
            worst = max(worst, ratio)
        return worst

    # -- the reconcile tick ------------------------------------------------
    def tick(self):
        """One sense -> degrade -> scale pass; returns a summary dict
        (also retained for :meth:`state`)."""
        t0 = time.perf_counter()
        self._metrics.inc("controller.ticks")
        with _span("controller.tick"):
            scraper = self.router._scraper
            scrapes = scraper.scrape()
            rps, _tps = scraper.rates(scrapes)
            values = []
            if self._watchdog is not None:
                self._watchdog.maybe_evaluate()
                values = self._watchdog.last_values()
            pressure = self._pressure(values)
            self.last_pressure = pressure
            self.last_rps = rps
            self._update_ladder(pressure)
            promoted = self._maybe_scale_up(pressure)
            drained = self._maybe_scale_down(rps)
            self._ensure_standbys()
            with self._lock:
                self._metrics.set_gauge("controller.standbys_ready",
                                        len(self._standbys))
        self._metrics.observe("controller.tick_seconds",
                              time.perf_counter() - t0)
        return {"pressure": pressure, "rps": rps,
                "degrade_level": self._level,
                "promoted": promoted, "drained": drained}

    # -- graceful degradation ----------------------------------------------
    def _update_ladder(self, pressure):
        deg = self.policy.degrade
        ladder = deg["ladder"]
        stepped = False
        if pressure >= deg["engage_ratio"]:
            self._healthy_ticks = 0
            if self._level < len(ladder) - 1:
                self._level += 1
                stepped = True
        else:
            self._healthy_ticks += 1
            # hysteresis: climb immediately, descend only after
            # recover_ticks consecutive healthy ticks — the flap damper
            if self._level > 0 and \
                    self._healthy_ticks >= deg["recover_ticks"]:
                self._level -= 1
                self._healthy_ticks = 0
                stepped = True
        if stepped:
            self._metrics.inc("controller.degrade_steps")
        self._metrics.set_gauge("controller.degrade_level", self._level)
        self.router.set_admission(
            self._level, ladder[self._level],
            retry_after_s=deg["retry_after_seconds"],
            reason=f"slo pressure {pressure:.2f}" if self._level
            else "")

    # -- scale up ----------------------------------------------------------
    def _maybe_scale_up(self, pressure):
        up = self.policy.scale_up
        if pressure >= up["pressure_ratio"]:
            self._pressure_ticks += 1
        else:
            self._pressure_ticks = 0
            return None
        if self._pressure_ticks < up["sustained_ticks"]:
            return None
        now = time.monotonic()
        if now - self._last_scale_up < up["cooldown_seconds"]:
            return None
        if len(self.router.live_replicas()) >= self.policy.max_replicas:
            return None
        return self.scale_up(reason=f"slo pressure {pressure:.2f} for "
                                    f"{self._pressure_ticks} ticks")

    def scale_up(self, reason=""):
        """Promote one warm standby into the serving fleet (enroll =
        register + heartbeat; the router discovers it on its next
        poll).  Falls back to a synchronous warm when the pool is
        empty.  Returns the promoted replica, or None when promotion
        was impossible this tick (no factory, warm failure, or the
        ``fleet.scale.stall`` drill)."""
        from paddle_tpu.fault import chaos
        with _span("controller.scale_up", reason=reason):
            try:
                chaos.fire("fleet.scale.stall", reason=reason)
            except chaos.FaultInjected:
                # the machine-pool-exhausted drill: the decision is
                # lost this tick, pressure keeps it coming back
                self._metrics.inc("controller.scale_stalls")
                logger.warning("fleet.scale.stall fired: scale-up "
                               "lost this tick (%s)", reason)
                return None
            with self._lock:
                replica = self._standbys.pop() if self._standbys \
                    else None
            if replica is None:
                # cold fallback: no standby ready (warm thread still
                # working, or the pool is disabled) — pay the warm now
                # rather than not scaling at all
                replica = self._warm_one()
                if replica is None:
                    return None
            try:
                replica.enroll()
            except Exception:
                logger.exception("scale-up enroll failed for replica "
                                 "%s", replica.replica_id)
                try:
                    replica.drain()
                except Exception:
                    pass
                return None
            with self._lock:
                self._owned.append(replica)
            self._last_scale_up = time.monotonic()
            self._pressure_ticks = 0
            self._metrics.inc("controller.scale_ups")
            logger.info("scaled up: replica %s enrolled (%s)",
                        replica.replica_id, reason or "manual")
            return replica

    # -- scale down --------------------------------------------------------
    def _maybe_scale_down(self, rps):
        down = self.policy.scale_down
        live = len(self.router.live_replicas())
        with self._lock:
            owned = len(self._owned)
        if rps is None or live <= self.policy.min_replicas or not owned \
                or self._level > 0:
            # never drain while degraded: shedding + shrinking at the
            # same time is how oscillation starts
            self._idle_ticks = 0
            return None
        if rps / max(1, live) > down["idle_rps_per_replica"]:
            self._idle_ticks = 0
            return None
        self._idle_ticks += 1
        if self._idle_ticks < down["sustained_ticks"]:
            return None
        now = time.monotonic()
        if now - self._last_scale_down < down["cooldown_seconds"]:
            return None
        with self._lock:
            replica = self._owned.pop() if self._owned else None
        if replica is None:
            return None
        # LIFO: the most recently promoted replica leaves first — the
        # longest-lived replicas keep the warmest caches
        with _span("controller.drain", replica=replica.replica_id):
            try:
                replica.drain()
            except Exception:
                logger.exception("scale-down drain failed for replica "
                                 "%s", replica.replica_id)
        self._last_scale_down = now
        self._idle_ticks = 0
        self._metrics.inc("controller.scale_downs")
        logger.info("scaled down: replica %s drained",
                    replica.replica_id)
        return replica

    # -- standby pool ------------------------------------------------------
    def _warm_one(self):
        """Warm one standby through the factory (and, when
        ``PADDLE_TPU_COMPILE_CACHE`` is set, through the persistent
        compile cache).  Returns the warmed replica or None on
        failure — including the ``fleet.standby.fail`` drill."""
        from paddle_tpu.fault import chaos
        if self._standby_factory is None:
            return None
        replica = None
        try:
            chaos.fire("fleet.standby.fail")
            replica = self._standby_factory()
            replica.warm(self.policy.ready_timeout)
            self._metrics.inc("controller.standbys_warmed")
            return replica
        except Exception:
            self._metrics.inc("controller.standby_warm_failures")
            logger.exception("standby warm failed")
            if replica is not None:
                try:
                    replica.drain()
                except Exception:
                    pass
            return None

    def _ensure_standbys(self):
        """Keep the standby pool at its target size, one background
        warm at a time — a warm is seconds even through the compile
        cache, and the tick must never block on it."""
        with self._lock:
            if (self._warming or self._standby_factory is None
                    or len(self._standbys) >= self.policy.standby_pool):
                return
            self._warming = True

        def work():
            try:
                replica = self._warm_one()
                if replica is not None:
                    with self._lock:
                        self._standbys.append(replica)
            finally:
                with self._lock:
                    self._warming = False

        threading.Thread(target=work, daemon=True,
                         name="fleet-standby-warm").start()

    def prewarm(self, count=None, raise_on_failure=True):
        """Synchronously fill the standby pool (``count`` defaults to
        the policy's ``standby_pool``) — the pre-launch step that makes
        the FIRST scale-up warm too.  Returns the number warmed."""
        want = self.policy.standby_pool if count is None else int(count)
        warmed = 0
        while True:
            with self._lock:
                if len(self._standbys) >= want:
                    break
            replica = self._warm_one()
            if replica is None:
                if raise_on_failure:
                    raise RuntimeError(
                        "standby prewarm failed (no factory, warm "
                        "error, or fleet.standby.fail armed)")
                break
            with self._lock:
                self._standbys.append(replica)
            warmed += 1
        with self._lock:
            self._metrics.set_gauge("controller.standbys_ready",
                                    len(self._standbys))
        return warmed

    # -- state / lifecycle -------------------------------------------------
    def state(self):
        """JSON-able controller summary (for tests, the CLI, and
        operator probes)."""
        with self._lock:
            standbys = [r.replica_id for r in self._standbys]
            owned = [r.replica_id for r in self._owned]
        return {"policy": self.policy.to_dict(),
                "degrade_level": self._level,
                "admission": self.router.admission_state(),
                "pressure": self.last_pressure,
                "rps": self.last_rps,
                "standbys": standbys,
                "owned": owned,
                "live_replicas": len(self.router.live_replicas())}

    def start(self, interval=None):
        """Background reconcile thread; idempotent."""
        if self._thread is not None:
            return self._thread
        period = float(interval if interval is not None
                       else self.policy.interval)

        def loop():
            while not self._stop.wait(period):
                try:
                    self.tick()
                except Exception:  # pragma: no cover - must never die
                    logger.exception("fleet controller tick failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-controller")
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def shutdown(self, drain_owned=False):
        """Stop the loop and tear down the standby pool (warmed-but-
        unenrolled listeners would otherwise leak).  With
        ``drain_owned`` the controller also drains every replica it
        promoted — bench/test cleanup; production rolldowns usually
        leave the serving fleet up."""
        self.stop()
        with self._lock:
            standbys, self._standbys = self._standbys, []
            owned = list(self._owned) if drain_owned else []
            if drain_owned:
                self._owned = []
        for replica in standbys + owned:
            try:
                replica.drain()
            except Exception:
                pass
