"""Traffic-replay harness: the load side of the fleet control loop.

A controller that only ever sees closed-loop bench clients is untested
where it matters — closed-loop load self-throttles exactly when the
fleet saturates (each client waits for its reply before sending the
next request), hiding the overload the controller exists to survive.
:class:`TrafficReplay` is OPEN-LOOP: arrivals are a Poisson process
whose rate follows a deterministic pattern function, independent of
how the fleet is coping, which is how real traffic behaves.

Patterns are plain ``t_seconds -> rps`` callables; :func:`step`
(the 5× ramp drill), :func:`diurnal` (slow sinusoidal swell), and
:func:`flash_crowd` (instant spike, exponential decay) cover the
shapes the autoscaler must survive.  :func:`heavy_tail_lengths` gives
a seeded lognormal prompt-length mix — the heavy tail is what makes
per-request cost non-uniform, which is what makes placement matter.

Every request is metered (``traffic.*`` counters + the
``traffic.request`` span) and classified:

- ``ok`` — 200.
- ``shed`` — 429: the fleet said "not now" WITH a pacing hint; the
  summary splits sheds by whether ``Retry-After`` was present, because
  a shed without a hint is a bug (the acceptance criterion).
- ``deadline`` — 504: the budget burned in a queue, the outcome
  admission control exists to prevent.
- ``error`` — transport failure or any other status: a LOST accepted
  request (the chaos drill's zero-loss criterion counts these).
- ``dropped`` — never sent: the replayer's own inflight cap was hit
  (client-side protection; not a fleet failure).

All randomness is seeded — two runs with the same seed replay the
same arrival schedule and prompt mix, so A/B runs (fixed fleet vs
controller fleet) see identical offered load.
"""

from __future__ import annotations

import math
import random
import threading
import time

from paddle_tpu.obs.trace import span as _span

__all__ = ["TrafficReplay", "step", "diurnal", "flash_crowd",
           "heavy_tail_lengths"]


# ---------------------------------------------------------------------------
# rate patterns (t_seconds -> requests/sec)
# ---------------------------------------------------------------------------

def step(base_rps, peak_rps, at, duration=None):
    """Flat ``base_rps``, then a hard step to ``peak_rps`` at ``at``
    seconds (optionally stepping back down after ``duration``) — the
    "did the autoscaler keep up with a 5× step" drill."""
    base, peak, at = float(base_rps), float(peak_rps), float(at)

    def rate(t):
        if t < at:
            return base
        if duration is not None and t >= at + float(duration):
            return base
        return peak

    return rate


def diurnal(base_rps, peak_rps, period=60.0, phase=0.0):
    """Sinusoidal swell between ``base_rps`` and ``peak_rps`` over
    ``period`` seconds — the compressed day/night cycle (starts at the
    trough with ``phase=0``)."""
    base, peak = float(base_rps), float(peak_rps)
    period = float(period)

    def rate(t):
        frac = 0.5 * (1.0 - math.cos(
            2.0 * math.pi * (t + phase) / period))
        return base + (peak - base) * frac

    return rate


def flash_crowd(base_rps, peak_rps, at, rise=0.5, fall=5.0):
    """Flat ``base_rps`` until ``at``, a near-instant ramp to
    ``peak_rps`` over ``rise`` seconds, then exponential decay back
    with time-constant ``fall`` — the "a link went viral" shape that
    is too fast to scale for, i.e. the admission ladder's moment."""
    base, peak, at = float(base_rps), float(peak_rps), float(at)
    rise, fall = max(1e-6, float(rise)), max(1e-6, float(fall))

    def rate(t):
        if t < at:
            return base
        if t < at + rise:
            return base + (peak - base) * (t - at) / rise
        return base + (peak - base) * math.exp(-(t - at - rise) / fall)

    return rate


def heavy_tail_lengths(n, seed=0, median=32, sigma=1.0, cap=512):
    """``n`` seeded lognormal prompt lengths (median ``median`` tokens,
    shape ``sigma``, clamped to ``[1, cap]``) — the heavy-tailed mix
    where a p99 prompt costs ~10× a median one."""
    rng = random.Random(seed)
    mu = math.log(max(1.0, float(median)))
    return [max(1, min(int(cap),
                       int(round(rng.lognormvariate(mu, sigma)))))
            for _ in range(int(n))]


# ---------------------------------------------------------------------------
# the replayer
# ---------------------------------------------------------------------------

class TrafficReplay:
    """Open-loop Poisson replay of a rate pattern against a fleet.

    ``send(i)`` performs ONE request (the bench wires an HTTP POST to
    the router here) and returns ``{"status": int, "retry_after":
    str | None, ...}``; raising classifies the request as ``error``.
    ``pattern`` is a ``t_seconds -> rps`` callable; ``duration`` bounds
    the replay; ``seed`` fixes the arrival schedule.  ``max_inflight``
    bounds the replayer's own thread fan-out — arrivals past the cap
    are counted ``dropped``, never silently skipped."""

    def __init__(self, send, pattern, duration, seed=0,
                 max_inflight=64, metrics=None):
        self._send = send
        self._pattern = pattern
        self._duration = float(duration)
        self._seed = int(seed)
        self._max_inflight = max(1, int(max_inflight))
        if metrics is None:
            from paddle_tpu.profiler import runtime_metrics
            metrics = runtime_metrics
        self._metrics = metrics
        self._lock = threading.Lock()
        self._inflight = 0
        self.outcomes = []   # (outcome, latency_s, retry_after | None)

    # -- one request --------------------------------------------------------
    def _classify(self, result):
        status = result.get("status")
        if status == 200:
            return "ok"
        if status in (429, 503):
            # backpressure: admission shed (429) or the router giving
            # up retryably (503) — both tell the caller to come back,
            # both must carry Retry-After, neither is a lost request
            return "shed"
        if status == 504:
            return "deadline"
        return "error"

    def _one(self, i):
        t0 = time.perf_counter()
        try:
            with _span("traffic.request", index=i):
                result = self._send(i) or {}
            outcome = self._classify(result)
            hint = result.get("retry_after")
        except Exception as e:
            outcome, hint = "error", None
            result = {"exception": repr(e)}
        latency = time.perf_counter() - t0
        self._metrics.observe("traffic.request_seconds", latency)
        if outcome == "ok":
            self._metrics.inc("traffic.ok")
        elif outcome == "shed":
            self._metrics.inc("traffic.shed")
        elif outcome == "deadline":
            self._metrics.inc("traffic.deadline_exceeded")
        else:
            self._metrics.inc("traffic.errors")
        with self._lock:
            self.outcomes.append((outcome, latency, hint))
            self._inflight -= 1

    # -- the replay loop ----------------------------------------------------
    def run(self):
        """Replay the full schedule; returns :meth:`summary`.  Blocks
        until every dispatched request has completed — the tail of the
        last in-flight work belongs to the measurement."""
        rng = random.Random(self._seed)
        threads = []
        t_start = time.monotonic()
        next_at = 0.0
        i = 0
        while True:
            rate = max(0.0, float(self._pattern(next_at)))
            if rate <= 0.0:
                # idle stretch of the pattern: walk time forward until
                # the rate comes back (or the replay ends)
                next_at += 0.05
            else:
                next_at += rng.expovariate(rate)
            if next_at >= self._duration:
                break
            if rate <= 0.0:
                continue
            delay = t_start + next_at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            self._metrics.inc("traffic.sent")
            with self._lock:
                over = self._inflight >= self._max_inflight
                if not over:
                    self._inflight += 1
            if over:
                # open-loop protection: the fleet is so far behind that
                # the replayer would hoard threads — count it, loudly
                self._metrics.inc("traffic.dropped")
                with self._lock:
                    self.outcomes.append(("dropped", 0.0, None))
                i += 1
                continue
            t = threading.Thread(target=self._one, args=(i,),
                                 daemon=True,
                                 name=f"traffic-replay-{i}")
            t.start()
            threads.append(t)
            i += 1
        for t in threads:
            t.join(timeout=60.0)
        return self.summary()

    # -- results ------------------------------------------------------------
    def summary(self):
        """Aggregate the replay: per-outcome counts, the
        with/without-``Retry-After`` shed split, and latency
        percentiles over completed (ok) requests."""
        with self._lock:
            outcomes = list(self.outcomes)
        counts = {"ok": 0, "shed": 0, "deadline": 0, "error": 0,
                  "dropped": 0}
        shed_with_hint = shed_without_hint = 0
        ok_lat = []
        for outcome, latency, hint in outcomes:
            counts[outcome] = counts.get(outcome, 0) + 1
            if outcome == "ok":
                ok_lat.append(latency)
            elif outcome == "shed":
                if hint:
                    shed_with_hint += 1
                else:
                    shed_without_hint += 1
        ok_lat.sort()

        def pct(q):
            if not ok_lat:
                return None
            return ok_lat[min(len(ok_lat) - 1,
                              int(q / 100.0 * len(ok_lat)))]

        return {"attempted": len(outcomes),
                "outcomes": counts,
                "shed_with_hint": shed_with_hint,
                "shed_without_hint": shed_without_hint,
                "lost_accepted": counts["error"] + counts["deadline"],
                "latency_ms": {"p50": (pct(50) or 0.0) * 1e3,
                               "p99": (pct(99) or 0.0) * 1e3}}
