"""Go-style CSP channels (reference ``paddle/fluid/framework/channel.h:33``
/ ``channel_impl.h``, semantics pinned by ``channel_test.cc``).

Host-side concurrency primitives (the reference's are C++ threads +
condition variables; here Python threads — channels coordinate *host*
control flow, they are not a device-compute path):

* capacity == 0 → unbuffered rendezvous: ``send`` blocks until a receiver
  takes the value, ``receive`` blocks until a sender arrives.
* capacity > 0 → FIFO buffer: ``send`` blocks only when full.
* ``close``: further sends raise ``ChannelClosedError`` (panic semantics);
  blocked senders are woken with the same error; receivers drain residual
  buffered values, then get ``(zero, False)``.
* receive order == send order.
"""

from __future__ import annotations

import collections
import threading

__all__ = ["Channel", "ChannelClosedError"]


class ChannelClosedError(RuntimeError):
    """Send on a closed channel (reference: PADDLE_THROW 'Cannot send on
    closed channel', channel_impl.h)."""


class Channel:
    def __init__(self, capacity=0, dtype=None):
        self.capacity = int(capacity)
        self.dtype = dtype
        self._buf = collections.deque()
        self._closed = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # unbuffered rendezvous bookkeeping: #receivers waiting, and a
        # one-slot handoff queue consumed in FIFO order
        self._recv_waiting = 0

    # -- introspection (Channel::Cap/IsClosed/CanSend/CanReceive) ---------
    def cap(self):
        return self.capacity

    def is_closed(self):
        with self._lock:
            return self._closed

    def can_send(self):
        with self._lock:
            if self._closed:
                return False
            if self.capacity > 0:
                return len(self._buf) < self.capacity
            return self._recv_waiting > 0

    def can_receive(self):
        # non-empty buffer covers both buffered values and unbuffered
        # senders waiting at the rendezvous
        with self._lock:
            return bool(self._buf)

    # -- core ops ---------------------------------------------------------
    def send(self, value, timeout=None):
        with self._cond:
            if self._closed:
                raise ChannelClosedError("cannot send on closed channel")
            if self.capacity > 0:
                while len(self._buf) >= self.capacity and not self._closed:
                    if not self._cond.wait(timeout=timeout):
                        raise TimeoutError("channel send timed out")
                if self._closed:
                    raise ChannelClosedError("cannot send on closed channel")
                self._buf.append(value)
                self._cond.notify_all()
                return
            # unbuffered: enqueue the value; a receiver must take it before
            # this send returns (rendezvous)
            item = [value, False]  # [value, taken]
            self._buf.append(item)
            self._cond.notify_all()
            while not item[1]:
                if self._closed:
                    # close unblocks senders with a panic (channel_test.cc
                    # UnbufferedChannelCloseUnblocksSendersTest)
                    try:
                        self._buf.remove(item)
                    except ValueError:
                        pass
                    raise ChannelClosedError(
                        "cannot send on closed channel")
                if not self._cond.wait(timeout=timeout):
                    try:
                        self._buf.remove(item)
                    except ValueError:
                        pass
                    raise TimeoutError("channel send timed out")

    def receive(self, timeout=None):
        """Returns (value, ok).  ok=False means closed-and-drained."""
        with self._cond:
            if self.capacity > 0:
                while not self._buf and not self._closed:
                    if not self._cond.wait(timeout=timeout):
                        raise TimeoutError("channel receive timed out")
                if self._buf:
                    v = self._buf.popleft()
                    self._cond.notify_all()
                    return v, True
                return None, False  # closed and drained
            # unbuffered
            self._recv_waiting += 1
            try:
                while not self._buf and not self._closed:
                    if not self._cond.wait(timeout=timeout):
                        raise TimeoutError("channel receive timed out")
                if self._buf:
                    item = self._buf.popleft()
                    item[1] = True
                    self._cond.notify_all()
                    return item[0], True
                return None, False
            finally:
                self._recv_waiting -= 1

    def try_send(self, value):
        """Non-blocking send; True on success (select-case probe)."""
        with self._cond:
            if self._closed:
                raise ChannelClosedError("cannot send on closed channel")
            if self.capacity > 0:
                if len(self._buf) < self.capacity:
                    self._buf.append(value)
                    self._cond.notify_all()
                    return True
                return False
            if self._recv_waiting > 0 and not self._buf:
                item = [value, False]
                self._buf.append(item)
                self._cond.notify_all()
                # the waiting receiver will take it; from the select's
                # perspective the case fired
                return True
            return False

    def try_receive(self):
        """Non-blocking receive; (value, ok, ready)."""
        with self._cond:
            if self._buf:
                if self.capacity > 0:
                    v = self._buf.popleft()
                    self._cond.notify_all()
                    return v, True, True
                item = self._buf.popleft()
                item[1] = True
                self._cond.notify_all()
                return item[0], True, True
            if self._closed:
                return None, False, True  # closed fires immediately
            return None, False, False

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
