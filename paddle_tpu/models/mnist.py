"""MNIST convnet (reference ``benchmark/fluid/mnist.py`` — the minimum
end-to-end slice, SURVEY.md §7 milestone A)."""

from __future__ import annotations

import paddle_tpu.layers as layers
import paddle_tpu.nets as nets


def cnn_model(data):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return layers.fc(input=conv_pool_2, size=10, act="softmax")


def mnist_train_program(batch_size):
    image = layers.data(name="pixel", shape=[batch_size, 1, 28, 28],
                        dtype="float32", append_batch_size=False)
    label = layers.data(name="label", shape=[batch_size, 1], dtype="int64",
                        append_batch_size=False)
    predict = cnn_model(image)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=predict, label=label)
    return avg_cost, acc, ["pixel", "label"]
