"""Attention seq2seq NMT — the reference benchmark workload
``benchmark/fluid/machine_translation.py`` (bi-LSTM encoder + DynamicRNN
decoder with Bahdanau-style additive attention), re-built on the
TPU-native layers.

Per decoder step: the decoder state expands over the encoder tokens
(``sequence_expand``), an additive score per token feeds
``sequence_softmax``, and the attention-weighted sum of encoder states
becomes the context vector — the same op chain the reference composes,
each op a traced TPU lowering (the whole decoder is ONE bounded
lax.scan via the While lowering).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.layers as layers

__all__ = ["seq_to_seq_net", "fake_batch"]


def _bi_lstm_encoder(src_emb, size):
    fwd_proj = layers.fc(input=src_emb, size=size * 4, bias_attr=False)
    fwd_proj.lod_level = 1
    fwd, _ = layers.dynamic_lstm(input=fwd_proj, size=size * 4)
    rev_proj = layers.fc(input=src_emb, size=size * 4, bias_attr=False)
    rev_proj.lod_level = 1
    rev, _ = layers.dynamic_lstm(input=rev_proj, size=size * 4,
                                 is_reverse=True)
    return layers.concat([fwd, rev], axis=1)


def seq_to_seq_net(src_dict_size, trg_dict_size, emb_dim=32,
                   encoder_size=32, decoder_size=32):
    """Build the training graph; returns (avg_cost, prediction).

    Feeds: ``src_word`` / ``trg_word`` / ``label`` int64 [N, 1]
    lod_level=1 (label shares trg_word's lod).
    """
    src = layers.data(name="src_word", shape=[-1, 1], dtype="int64",
                      append_batch_size=False, lod_level=1)
    trg = layers.data(name="trg_word", shape=[-1, 1], dtype="int64",
                      append_batch_size=False, lod_level=1)
    label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)

    src_emb = layers.embedding(input=src, size=[src_dict_size, emb_dim])
    encoded = _bi_lstm_encoder(src_emb, encoder_size)   # [N, 2*enc]
    encoded.lod_level = 1
    # projection used by the additive attention score
    encoded_proj = layers.fc(input=encoded, size=decoder_size,
                             bias_attr=False)
    encoded_proj.lod_level = 1
    # decoder boot state from the encoder's last step
    enc_last = layers.sequence_last_step(encoded)
    boot = layers.fc(input=enc_last, size=decoder_size, act="tanh")

    trg_emb = layers.embedding(input=trg, size=[trg_dict_size, emb_dim])

    drnn = layers.DynamicRNN()
    with drnn.block():
        cur = drnn.step_input(trg_emb)                   # [B, emb]
        enc_vec = drnn.static_input(encoded)             # ragged [N, 2e]
        enc_proj = drnn.static_input(encoded_proj)       # ragged [N, d]
        hidden = drnn.memory(init=boot)                  # [B, d]
        # additive attention: score(tok) = v . tanh(proj_tok + W h)
        state_proj = layers.fc(input=hidden, size=decoder_size,
                               bias_attr=False)
        expanded = layers.sequence_expand(x=state_proj, y=enc_proj)
        att_in = layers.elementwise_add(enc_proj, expanded)
        att_in = layers.tanh(att_in)
        att_in.lod_level = 1
        scores = layers.fc(input=att_in, size=1, bias_attr=False)
        scores.lod_level = 1
        weights = layers.sequence_softmax(scores)        # ragged [N, 1]
        weighted = layers.elementwise_mul(enc_vec, weights, axis=0)
        weighted.lod_level = 1
        context = layers.sequence_pool(weighted, "sum")  # [B, 2e]
        new_hidden = layers.fc(input=[cur, context, hidden],
                               size=decoder_size, act="tanh")
        drnn.update_memory(hidden, new_hidden)
        out = layers.fc(input=new_hidden, size=trg_dict_size,
                        act="softmax")
        drnn.output(out)
    prediction = drnn()

    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(x=cost)
    return avg_cost, prediction


def fake_batch(batch, src_max, trg_max, src_dict, trg_dict, seed=0):
    """Deterministic learnable toy task: trg[t] = f(trg[t-1], src[0])."""
    rng = np.random.RandomState(seed)
    s_lens = rng.randint(2, src_max + 1, batch)
    t_lens = rng.randint(2, trg_max + 1, batch)
    s_splits = np.concatenate([[0], np.cumsum(s_lens)])
    t_splits = np.concatenate([[0], np.cumsum(t_lens)])
    src = rng.randint(0, src_dict, (s_splits[-1], 1)).astype("int64")
    trg_rows, lab_rows = [], []
    for b in range(batch):
        first_src = int(src[s_splits[b], 0])
        seq = [1]
        for _ in range(t_lens[b] - 1):
            seq.append((seq[-1] * 3 + first_src + 1) % trg_dict)
        trg_rows += seq
        lab_rows += seq[1:] + [(seq[-1] * 3 + first_src + 1) % trg_dict]
    return {
        "src_word": (src, [[int(s) for s in s_splits]]),
        "trg_word": (np.asarray(trg_rows, "int64").reshape(-1, 1),
                     [[int(s) for s in t_splits]]),
        "label": (np.asarray(lab_rows, "int64").reshape(-1, 1),
                  [[int(s) for s in t_splits]]),
    }
