"""Wide & Deep CTR model (Cheng et al. 2016) — the sharded-embedding
flagship.

PaddlePaddle's defining production workload: sparse id features hit
embedding tables too big for one host, so both tables are built with
``is_distributed=True`` — ``embedding.plan_sharded_tables`` (or the
``DistributeTranspiler`` sparse branch) then shards their vocab dim
over the mesh, and ``is_sparse=True`` makes the backward emit
SelectedRows so the optimizer touches only the rows a batch
referenced.

Geometry notes for the zoo gates: the default ``vocab_size`` stays
divisible by the selfcheck distribute drill's 2 shards AND the bench's
dp4 mesh, and all leading param dims are even so ``shard_params=True``
transpiles cleanly.
"""

from __future__ import annotations

import paddle_tpu.layers as layers

#: one shared default geometry for the zoo entry, selfcheck's
#: distribute drill, and bench_embedding's smoke mode
DEFAULT_VOCAB = 64


def wide_and_deep_train_program(batch_size, vocab_size=DEFAULT_VOCAB,
                                num_slots=4, emb_dim=8, dense_dim=8,
                                hidden=16):
    """CTR click prediction: ``num_slots`` sparse id features + a dense
    feature vector -> P(click).  Returns ``(avg_cost, acc,
    feed_names)`` like every zoo builder.

    * **deep**: per-slot ``emb_dim`` embeddings (the sharded table),
      concatenated with the dense features, through two relu FCs;
    * **wide**: a second ``[vocab, 1]`` table — the linear
      cross-feature term — sum-pooled over slots;
    * head: wide + deep concatenated into a 2-way softmax vs the
      click label.
    """
    slot_ids = layers.data(name="slot_ids",
                           shape=[batch_size, num_slots, 1],
                           dtype="int64", append_batch_size=False)
    dense = layers.data(name="dense", shape=[batch_size, dense_dim],
                        dtype="float32", append_batch_size=False)
    label = layers.data(name="label", shape=[batch_size, 1],
                        dtype="int64", append_batch_size=False)

    # deep side: the big table — sharded over the mesh, sparse grads
    deep_emb = layers.embedding(
        slot_ids, size=[vocab_size, emb_dim], is_sparse=True,
        is_distributed=True, param_attr="wide_deep_emb")
    deep_in = layers.reshape(deep_emb,
                             [batch_size, num_slots * emb_dim])
    deep = layers.concat([deep_in, dense], axis=1)
    deep = layers.fc(deep, hidden, act="relu")
    deep = layers.fc(deep, hidden, act="relu")

    # wide side: per-id linear weights, same sharded-table treatment
    wide_emb = layers.embedding(
        slot_ids, size=[vocab_size, 1], is_sparse=True,
        is_distributed=True, param_attr="wide_lr_w")
    wide = layers.reshape(wide_emb, [batch_size, num_slots])
    wide = layers.reduce_sum(wide, dim=1, keep_dim=True)

    joint = layers.concat([wide, deep], axis=1)
    predict = layers.fc(joint, 2, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=predict, label=label)
    return avg_cost, acc, ["slot_ids", "dense", "label"]
