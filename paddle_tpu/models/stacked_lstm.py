"""Stacked dynamic-LSTM sentiment classifier — the reference benchmark
workload ``benchmark/fluid/stacked_dynamic_lstm.py`` (an IMDB-style
classifier: embedding -> fc -> N stacked LSTMs over the ragged sequence
-> last+max pooling -> softmax), re-built on the TPU-native layers.

The reference hand-writes its LSTM gates inside a DynamicRNN; here each
layer is one ``dynamic_lstm`` op (a single fused lax.scan on TPU —
same math, one compiled loop instead of per-step op dispatch).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.layers as layers

__all__ = ["stacked_lstm_net", "fake_batch"]


def stacked_lstm_net(dict_size, emb_dim=64, hidden_dim=64, n_layers=2,
                     class_num=2):
    """Build the classifier; returns (avg_cost, accuracy, prediction).

    Feeds: ``words`` int64 [N, 1] lod_level=1, ``label`` int64 [B, 1].
    """
    words = layers.data(name="words", shape=[-1, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
    label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                        append_batch_size=False)
    emb = layers.embedding(input=words, size=[dict_size, emb_dim])
    h = layers.fc(input=emb, size=hidden_dim, act="tanh")
    h.lod_level = 1
    for _ in range(n_layers):
        proj = layers.fc(input=h, size=hidden_dim * 4)
        proj.lod_level = 1
        h, _ = layers.dynamic_lstm(input=proj, size=hidden_dim * 4)
    last = layers.sequence_last_step(h)
    mx = layers.sequence_pool(h, "max")
    feat = layers.concat([last, mx], axis=1)
    prediction = layers.fc(input=feat, size=class_num, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction


def fake_batch(batch, max_len, dict_size, seed=0):
    """Synthetic learnable batch: the label is a parity-style function of
    the word ids, so the classifier can overfit it."""
    rng = np.random.RandomState(seed)
    lengths = rng.randint(2, max_len + 1, batch)
    splits = np.concatenate([[0], np.cumsum(lengths)])
    words = rng.randint(0, dict_size, (splits[-1], 1)).astype("int64")
    labels = np.array([
        int(words[splits[i]:splits[i + 1]].sum() % 2)
        for i in range(batch)], "int64").reshape(-1, 1)
    return {"words": (words, [[int(s) for s in splits]]),
            "label": labels}
