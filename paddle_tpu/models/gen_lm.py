"""Generative causal LM with a prefill/decode phase split — the model
side of the continuous-batching serving runtime (``paddle_tpu/gen/``).

One set of parameters (shared names) is exported as TWO inference
programs, the vLLM/Orca-style entry pair:

* **prefill** — batch of ONE prompt, dynamic (bucketed) length: runs the
  full causal forward over the prompt, fetches the next-token logits at
  the last real position plus the per-layer K/V projections (masked to
  zero on pad rows) that seed the request's KV-cache slot.  The length
  axis is dynamic; callers pad to a ``lod.row_bucket`` edge so the jit
  key is the bucket, not the exact prompt length.
* **decode** — ONE token for every slot of a fixed cache pool
  ``[num_slots, max_len]``: reads the persistable cache tensors, writes
  the new token's K/V at its position via a position-one-hot outer
  product (an in-place persistable update, so the cache never leaves
  the device), and attends over the full cache under a runtime length
  mask.  Every decode step has the SAME signature — admission and
  eviction never recompile.

The default export uses the PAGED decode variant
(:func:`build_paged_decode_program`): the cache pool lives as
``[num_pages, page_len, H*D]`` fixed-size pages plus a per-slot page
table, and each step attends only the pages covering ``[0, len)`` per
slot — decode reads scale with live prefix length instead of the padded
``max_len`` (ROADMAP item 3).  The page-table feed's width is bucketed
(``page_buckets``) so the jit key stays constant per bucket; the dense
variant remains exportable with ``paged=False`` (the equivalence
baseline and bench comparison point).

The third entry, :func:`gen_lm_train_program`, is the teacher-forced
training graph over the same parameter names (and the model-zoo lint
gate's view of this model).
"""

from __future__ import annotations

import json
import os

import numpy as np

import paddle_tpu.layers as layers
from paddle_tpu.initializer import NumpyArrayInitializer
from paddle_tpu.param_attr import ParamAttr

__all__ = ["GenConfig", "build_prefill_program", "build_decode_program",
           "build_paged_decode_program", "gen_lm_train_program",
           "export_gen_model", "META_FILENAME", "PAGE_LEN_DEFAULT",
           "paged_cache_var_names", "default_page_buckets"]

META_FILENAME = "gen_meta.json"

#: default KV page length (rows per page) for paged exports
PAGE_LEN_DEFAULT = 16


class GenConfig:
    """Toy-scale causal LM hyperparameters (decode mechanics, not model
    quality, are what the gen runtime exercises)."""
    vocab_size = 64
    d_model = 32
    n_head = 2
    d_head = 16          # n_head * d_head == d_model
    n_layer = 2
    d_ffn = 64
    max_len = 64         # cache length L (bucketed max sequence length)
    eos_id = -1          # <0: no EOS in the base model (requests may
                         # override per call)


def _pa(name, **kw):
    return ParamAttr(name=name, **kw)


def _pos_table(hp):
    from paddle_tpu.models.transformer import position_encoding_init
    return position_encoding_init(hp.max_len, hp.d_model)


def _embed(ids, pos_ids, hp):
    """Shared token + position embedding (works for [B, T] prefill ids
    and [S, 1] decode ids — lookup_table squeezes a trailing 1)."""
    word = layers.embedding(ids, size=[hp.vocab_size, hp.d_model],
                            param_attr=_pa("genlm_word_emb"))
    word = layers.scale(word, scale=float(hp.d_model) ** 0.5)
    pos = layers.embedding(
        pos_ids, size=[hp.max_len, hp.d_model],
        param_attr=_pa("genlm_pos_emb", trainable=False,
                       initializer=NumpyArrayInitializer(_pos_table(hp))))
    return word + pos


def _ln(x, idx, tag):
    return layers.layer_norm(
        x, begin_norm_axis=len(x.shape) - 1,
        param_attr=_pa(f"genlm{idx}_{tag}.scale"),
        bias_attr=_pa(f"genlm{idx}_{tag}.bias"))


def _ffn(x, hp, idx):
    h = layers.fc(x, hp.d_ffn, num_flatten_dims=2, act="relu",
                  param_attr=_pa(f"genlm{idx}_ffn1.w"),
                  bias_attr=_pa(f"genlm{idx}_ffn1.b"))
    return layers.fc(h, hp.d_model, num_flatten_dims=2,
                     param_attr=_pa(f"genlm{idx}_ffn2.w"),
                     bias_attr=_pa(f"genlm{idx}_ffn2.b"))


def _qkv(x, hp, idx):
    """Q/K/V projections over [B, T, d] (or [S, 1, d])."""
    def proj(role):
        return layers.fc(x, hp.n_head * hp.d_head, num_flatten_dims=2,
                         bias_attr=False,
                         param_attr=_pa(f"genlm{idx}_{role}.w"))
    return proj("q"), proj("k"), proj("v")


def _heads(x, hp, length):
    """[B, T, H*D] -> [B, H, T, D]; ``length`` may be -1 (dynamic)."""
    x = layers.reshape(x, shape=[x.shape[0], length, hp.n_head, hp.d_head])
    return layers.transpose(x, perm=[0, 2, 1, 3])


def _merge_heads(ctx, hp, length):
    """[B, H, T, D] -> [B, T, H*D]."""
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    return layers.reshape(
        ctx, shape=[ctx.shape[0], length, hp.n_head * hp.d_head])


def _attend(q, k, v, bias, hp, idx, q_len, k_len):
    """Scaled-dot-product attention with an additive ``bias`` mask
    (broadcastable against [B, H, Sq, Sk] scores)."""
    scale = float(hp.d_head) ** -0.5
    qh = _heads(q, hp, q_len)
    kh = _heads(k, hp, k_len)
    vh = _heads(v, hp, k_len)
    scores = layers.matmul(qh, kh, transpose_y=True, alpha=scale)
    weights = layers.softmax(scores, bias=bias)
    ctx = layers.matmul(weights, vh)
    ctx = _merge_heads(ctx, hp, q_len)
    return layers.fc(ctx, hp.d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=_pa(f"genlm{idx}_attnout.w"))


def _block_tail(x, attn, hp, idx):
    x = _ln(x + attn, idx, "ln1")
    return _ln(x + _ffn(x, hp, idx), idx, "ln2")


def cache_var_names(hp):
    """The decode program's persistable KV-cache tensor names, in the
    (k, v) per-layer order the prefill fetch list follows."""
    names = []
    for i in range(hp.n_layer):
        names.append(f"genlm_cache_k_{i}")
        names.append(f"genlm_cache_v_{i}")
    return names


def paged_cache_var_names(hp):
    """The PAGED decode program's persistable page-pool tensor names,
    in the same (k, v) per-layer order as :func:`cache_var_names`."""
    names = []
    for i in range(hp.n_layer):
        names.append(f"genlm_paged_k_{i}")
        names.append(f"genlm_paged_v_{i}")
    return names


def default_page_buckets(pages_per_slot):
    """Power-of-two page-count bucket ladder capped at ``pages_per_slot``
    (NOT :func:`lod.bucket_edges`, whose fallback ladder floors at 8 —
    page counts are small integers).  ``GenPredictor.plan_page_buckets``
    replaces this with a measured-workload ladder."""
    edges, b = [], 1
    while b < int(pages_per_slot):
        edges.append(b)
        b *= 2
    edges.append(int(pages_per_slot))
    return sorted(set(edges))


# ---------------------------------------------------------------------------
# prefill: one prompt, dynamic (bucketed) length
# ---------------------------------------------------------------------------

def build_prefill_program(hp):
    """Build the prefill forward in the CURRENT program guard.

    Feeds (all length-dynamic; callers pad to a bucket):
      ``gen_ids`` [1, T] int32, ``gen_pos`` [1, T] int32,
      ``gen_mask`` [1, T] f32 (1 = real token),
      ``gen_attn_bias`` [1, 1, T, T] f32 (combined causal+padding
      additive bias), ``gen_last`` [1, T] f32 (one-hot of the last real
      position).
    Fetches: ``[logits [1, V], k_0, v_0, k_1, v_1, ...]`` with each
    K/V [1, T, H*D] zeroed on pad rows (cache hygiene: decode add-writes
    land on zeros).
    """
    def data(name, shape, dtype="float32"):
        return layers.data(name=name, shape=shape, dtype=dtype,
                           append_batch_size=False)

    ids = data("gen_ids", [1, -1], "int32")
    pos = data("gen_pos", [1, -1], "int32")
    mask = data("gen_mask", [1, -1])
    bias = data("gen_attn_bias", [1, 1, -1, -1])
    last = data("gen_last", [1, -1])

    x = _embed(ids, pos, hp)
    kv = []
    for i in range(hp.n_layer):
        q, k, v = _qkv(x, hp, i)
        k_m = layers.elementwise_mul(k, mask, axis=0)
        v_m = layers.elementwise_mul(v, mask, axis=0)
        kv += [k_m, v_m]
        attn = _attend(q, k_m, v_m, bias, hp, i, q_len=-1, k_len=-1)
        x = _block_tail(x, attn, hp, i)
    last3 = layers.reshape(last, shape=[1, 1, -1])
    lasth = layers.matmul(last3, x)                    # [1, 1, d]
    lasth = layers.reshape(lasth, shape=[-1, hp.d_model])
    logits = layers.fc(lasth, hp.vocab_size, bias_attr=False,
                       param_attr=_pa("genlm_logits.w"))
    feeds = ["gen_ids", "gen_pos", "gen_mask", "gen_attn_bias", "gen_last"]
    return feeds, [logits] + kv


# ---------------------------------------------------------------------------
# decode: one token for every cache slot, constant signature
# ---------------------------------------------------------------------------

def build_decode_program(hp, num_slots):
    """Build the single-token decode step in the CURRENT program guard.

    Feeds (ALL with static shapes — one jit signature forever):
      ``gen_token`` [S, 1] int32 (last emitted token per slot),
      ``gen_pos`` [S, 1] int32 (its position),
      ``gen_pos_onehot`` [S, L] f32 (1 at the write position for live
      slots, all-zero rows for free slots — the no-write mask),
      ``gen_attn_mask`` [S, L] f32 (1 = attendable cache position,
      INCLUDING the current token's own).
    Persistable state: per-layer ``genlm_cache_k_i`` / ``genlm_cache_v_i``
    [S, L, H*D], updated in place (the executor's donated inout path).
    Fetches: ``logits`` [S, V].
    """
    import paddle_tpu as fluid

    S, L = int(num_slots), int(hp.max_len)
    hd = hp.n_head * hp.d_head

    def data(name, shape, dtype="float32"):
        return layers.data(name=name, shape=shape, dtype=dtype,
                           append_batch_size=False)

    token = data("gen_token", [S, 1], "int32")
    pos = data("gen_pos", [S, 1], "int32")
    pos_onehot = data("gen_pos_onehot", [S, L])
    attn_mask = data("gen_attn_mask", [S, L])

    block = fluid.default_main_program().global_block()
    caches = {}
    for name in cache_var_names(hp):
        c = block.create_var(name=name, shape=[S, L, hd], dtype="float32")
        c.persistable = True
        c.stop_gradient = True
        caches[name] = c

    x = _embed(token, pos, hp)                         # [S, d]
    x = layers.reshape(x, shape=[S, 1, hp.d_model])
    po3 = layers.reshape(pos_onehot, shape=[S, L, 1])
    bias = layers.reshape(layers.scale(attn_mask, scale=1e9, bias=-1e9),
                          shape=[S, 1, 1, L])
    for i in range(hp.n_layer):
        q, k, v = _qkv(x, hp, i)                       # [S, 1, H*D]
        ck, cv = caches[f"genlm_cache_k_{i}"], caches[f"genlm_cache_v_{i}"]
        # scatter the new token's K/V into its cache position: an outer
        # product against the position one-hot, added IN PLACE (free
        # slots feed an all-zero one-hot row, so nothing is written)
        for cache, new in ((ck, k), (cv, v)):
            delta = layers.matmul(po3, new)            # [S, L, H*D]
            block.append_op(type="elementwise_add",
                            inputs={"X": [cache.name], "Y": [delta.name]},
                            outputs={"Out": [cache.name]},
                            attrs={"axis": -1})
        # attention over the UPDATED cache (reads after the in-place
        # write observe the current token's own K/V)
        attn = _attend(q, ck, cv, bias, hp, i, q_len=1, k_len=L)
        x = _block_tail(x, attn, hp, i)
    x2 = layers.reshape(x, shape=[S, hp.d_model])
    logits = layers.fc(x2, hp.vocab_size, bias_attr=False,
                       param_attr=_pa("genlm_logits.w"))
    feeds = ["gen_token", "gen_pos", "gen_pos_onehot", "gen_attn_mask"]
    return feeds, [logits]


# ---------------------------------------------------------------------------
# paged decode: page-pool cache, page-table feed bucketed by page count
# ---------------------------------------------------------------------------

def build_paged_decode_program(hp, num_slots, page_len, num_pages):
    """Build the PAGED single-token decode step in the CURRENT program
    guard.

    Feeds (static except the bucketed page-table width):
      ``gen_token`` [S, 1] int32, ``gen_pos`` [S, 1] int32,
      ``gen_page_table`` [S, P] int32 — per-slot page ids in prefix
      order; ``P`` is DYNAMIC, padded by the predictor to a
      ``page_buckets`` edge so the jit key is the bucket,
      ``gen_lens`` [S, 1] int32 — rows INCLUDING the current token
      (0 = free slot: nothing written, logits garbage, never read).
    Persistable state: per-layer ``genlm_paged_k_i`` / ``genlm_paged_v_i``
    [num_pages, page_len, H*D], updated in place by the
    ``paged_attention`` op (scatter of the step's K/V row into the
    slot's tail page, then attention over ONLY the table's pages).
    Fetches: ``logits`` [S, V].
    """
    import paddle_tpu as fluid
    from paddle_tpu.layer_helper import LayerHelper

    S, PL, NP = int(num_slots), int(page_len), int(num_pages)
    hd = hp.n_head * hp.d_head

    def data(name, shape, dtype="float32"):
        return layers.data(name=name, shape=shape, dtype=dtype,
                           append_batch_size=False)

    token = data("gen_token", [S, 1], "int32")
    pos = data("gen_pos", [S, 1], "int32")
    page_table = data("gen_page_table", [S, -1], "int32")
    lens = data("gen_lens", [S, 1], "int32")

    block = fluid.default_main_program().global_block()
    caches = {}
    for name in paged_cache_var_names(hp):
        c = block.create_var(name=name, shape=[NP, PL, hd],
                             dtype="float32")
        c.persistable = True
        c.stop_gradient = True
        caches[name] = c

    x = _embed(token, pos, hp)                         # [S, d]
    x = layers.reshape(x, shape=[S, 1, hp.d_model])
    for i in range(hp.n_layer):
        q, k, v = _qkv(x, hp, i)                       # [S, 1, H*D]
        pk = caches[f"genlm_paged_k_{i}"]
        pv = caches[f"genlm_paged_v_{i}"]
        helper = LayerHelper("paged_attention")
        ctxv = helper.create_tmp_variable("float32")
        helper.append_op(
            type="paged_attention",
            inputs={"Q": [q], "K": [k], "V": [v],
                    "KCache": [pk], "VCache": [pv],
                    "PageTable": [page_table], "Lens": [lens]},
            outputs={"Out": [ctxv], "KCacheOut": [pk], "VCacheOut": [pv]},
            attrs={"n_head": int(hp.n_head),
                   "scale": float(hp.d_head) ** -0.5})
        attn = layers.fc(ctxv, hp.d_model, num_flatten_dims=2,
                         bias_attr=False,
                         param_attr=_pa(f"genlm{i}_attnout.w"))
        x = _block_tail(x, attn, hp, i)
    x2 = layers.reshape(x, shape=[S, hp.d_model])
    logits = layers.fc(x2, hp.vocab_size, bias_attr=False,
                       param_attr=_pa("genlm_logits.w"))
    feeds = ["gen_token", "gen_pos", "gen_page_table", "gen_lens"]
    return feeds, [logits]


# ---------------------------------------------------------------------------
# training graph (teacher-forced) — also the model-zoo lint gate's view
# ---------------------------------------------------------------------------

def gen_lm_train_program(batch_size, seq_len, hp: GenConfig = None):
    """Causal-LM training forward in the current program guard; returns
    ``(avg_cost, feed_names)``.  Feeds: ``gen_ids`` / ``gen_labels``
    [B, T] int32."""
    hp = hp or GenConfig()
    B, T = int(batch_size), int(seq_len)

    ids = layers.data(name="gen_ids", shape=[B, T], dtype="int32",
                      append_batch_size=False)
    labels = layers.data(name="gen_labels", shape=[B, T], dtype="int32",
                         append_batch_size=False)
    pos_np = np.tile(np.arange(T, dtype="int32"), (B, 1))
    pos = layers.assign(pos_np)
    tri = np.triu(np.full((T, T), -1e9, dtype="float32"), 1)
    bias = layers.assign(tri.reshape(1, 1, T, T))

    x = _embed(ids, pos, hp)
    for i in range(hp.n_layer):
        q, k, v = _qkv(x, hp, i)
        attn = _attend(q, k, v, bias, hp, i, q_len=T, k_len=T)
        x = _block_tail(x, attn, hp, i)
    logits = layers.fc(x, hp.vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_pa("genlm_logits.w"))
    logits2d = layers.reshape(logits, shape=[B * T, hp.vocab_size])
    labels2d = layers.reshape(labels, shape=[B * T, 1])
    cost = layers.softmax_with_cross_entropy(logits2d, labels2d)
    avg_cost = layers.mean(x=cost)
    return avg_cost, ["gen_ids", "gen_labels"]


# ---------------------------------------------------------------------------
# export: one parameter set -> prefill/ + decode/ + gen_meta.json
# ---------------------------------------------------------------------------

def _write_model(dirname, program, feed_names, fetch_vars, executor):
    """The ``__model__`` + ``__params__`` pair ``io.load_inference_model``
    reads — written WITHOUT pruning (the decode program's in-place cache
    writes are load-bearing side effects a fetch-target prune would
    drop)."""
    from paddle_tpu import io as _io
    os.makedirs(dirname, exist_ok=True)
    model = {
        "program": program.to_dict(),
        "feed_var_names": list(feed_names),
        "fetch_var_names": [v.name for v in fetch_vars],
    }
    with open(os.path.join(dirname, "__model__"), "w") as f:
        json.dump(model, f)
    _io.save_persistables(executor, dirname, program, "__params__")


def export_gen_model(dirname, hp: GenConfig = None, num_slots=8,
                     prompt_buckets=None, paged=True,
                     page_len=PAGE_LEN_DEFAULT, num_pages=None,
                     page_buckets=None):
    """Export a generation bundle: ``<dirname>/prefill/``,
    ``<dirname>/decode/`` (each a loadable inference model over ONE
    shared parameter set) and ``<dirname>/gen_meta.json`` describing the
    cache pool geometry.  Returns ``dirname``.

    ``paged=True`` (the default) exports the page-pool decode variant:
    ``page_len`` rows per page (clamped to ``max_len``), ``num_pages``
    pool pages (default ``num_slots * ceil(max_len / page_len)`` — every
    slot can always grow to ``max_len``), ``page_buckets`` the declared
    page-count jit-signature ladder.  ``paged=False`` keeps the dense
    ``[num_slots, max_len]`` layout (the equivalence baseline)."""
    import paddle_tpu as fluid
    from paddle_tpu.lod import bucket_edges

    hp = hp or GenConfig()
    num_slots = int(num_slots)
    if prompt_buckets is None:
        prompt_buckets = bucket_edges(1, hp.max_len)
    if paged:
        page_len = max(1, min(int(page_len), int(hp.max_len)))
        pps = -(-int(hp.max_len) // page_len)
        num_pages = num_slots * pps if num_pages is None else int(num_pages)
        if page_buckets is None:
            page_buckets = default_page_buckets(pps)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        pre_main, pre_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(pre_main, pre_startup):
            pre_feeds, pre_fetches = build_prefill_program(hp)
        exe.run(pre_startup)
        _write_model(os.path.join(dirname, "prefill"), pre_main,
                     pre_feeds, pre_fetches, exe)

        dec_main, dec_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(dec_main, dec_startup):
            if paged:
                dec_feeds, dec_fetches = build_paged_decode_program(
                    hp, num_slots, page_len, num_pages)
            else:
                dec_feeds, dec_fetches = build_decode_program(hp,
                                                              num_slots)
        # decode shares the ALREADY-initialized parameters (its startup
        # is never run); the cache pool starts as zeros
        hd = hp.n_head * hp.d_head
        if paged:
            for name in paged_cache_var_names(hp):
                scope.set_var(name, np.zeros((num_pages, page_len, hd),
                                             dtype="float32"))
        else:
            for name in cache_var_names(hp):
                scope.set_var(name, np.zeros((num_slots, hp.max_len, hd),
                                             dtype="float32"))
        _write_model(os.path.join(dirname, "decode"), dec_main,
                     dec_feeds, dec_fetches, exe)

    meta = {
        "format": "paddle_tpu.gen/1",
        "num_slots": num_slots,
        "max_len": int(hp.max_len),
        "vocab_size": int(hp.vocab_size),
        "n_layer": int(hp.n_layer),
        "eos_id": int(hp.eos_id),
        "cache_vars": (paged_cache_var_names(hp) if paged
                       else cache_var_names(hp)),
        "prompt_buckets": [int(b) for b in prompt_buckets],
    }
    if paged:
        meta.update({
            "page_len": int(page_len),
            "num_pages": int(num_pages),
            "page_buckets": [int(b) for b in page_buckets],
            "page_table_feed": "gen_page_table",
        })
    with open(os.path.join(dirname, META_FILENAME), "w") as f:
        json.dump(meta, f, indent=2)
    # post-export contract (analysis/distributed.py): the bundle's
    # prefill/decode pair must satisfy the constant-jit-key contract
    # (static decode signature, cache geometry matching the meta,
    # prefill K/V fetches seeding exactly the cache) — a drifted
    # bundle fails HERE, at export, not at the first /generate;
    # unwarmable prompt buckets (the PTA018 recompile hazard) are
    # logged at warning level by the same check
    from paddle_tpu.analysis import verify_gen_bundle
    verify_gen_bundle(dirname, where="gen_lm.export_gen_model")
    return dirname
