"""Long-sequence flagship config of the generative LM (ROADMAP item 3):
the :mod:`gen_lm` architecture with ``max_len`` 256 — 4x the base
``GenConfig`` — the context length the PAGED KV layout exists for.  At
256 the dense decode pool reads ``num_slots * 256`` K/V rows per step
regardless of occupancy; the paged export reads only the live pages
(``docs/performance.md`` "Paged KV attention" has the occupancy math).

Registered in ``ZOO_MODELS`` so the lint gate, distribute/pipeline
splits, and the opt pipeline all cover the long-sequence geometry.
"""

from paddle_tpu.models import gen_lm

__all__ = ["GenLongConfig", "gen_lm_long_train_program"]


class GenLongConfig(gen_lm.GenConfig):
    """``GenConfig`` at flagship context length (>= 4x the base 64)."""
    max_len = 256


def gen_lm_long_train_program(batch_size, seq_len, hp: GenLongConfig = None):
    """Teacher-forced training forward at the long-context geometry;
    returns ``(avg_cost, feed_names)`` like
    :func:`gen_lm.gen_lm_train_program`."""
    return gen_lm.gen_lm_train_program(batch_size, seq_len,
                                       hp or GenLongConfig())
