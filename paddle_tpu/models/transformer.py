"""Transformer (Vaswani et al.) built on the Program IR layers.

The reference carries a full Transformer in its multi-device test
(``python/paddle/fluid/tests/unittests/test_parallel_executor.py:308``
``ModelHyperParams``/``transformer``) and benchmarks NMT under
``benchmark/fluid/machine_translation.py``.  This is the TPU-native
re-design: dense padded batches with explicit attention masks instead of
LoD ragged tensors, bfloat16-friendly matmuls that XLA tiles onto the MXU,
and one fused softmax(QK^T)V per head group.

Used as the flagship model for ``__graft_entry__.py`` / ``bench.py``
(north star: Transformer-base tokens/sec/chip, BASELINE.json).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.layers as layers
from paddle_tpu.initializer import NumpyArrayInitializer
from paddle_tpu.param_attr import ParamAttr


class ModelHyperParams:
    """Transformer-base (mirrors test_parallel_executor.py:308 defaults)."""
    src_vocab_size = 10000
    trg_vocab_size = 10000
    pos_pad_idx = 0
    src_pad_idx = 0
    trg_pad_idx = 0
    max_length = 256
    d_model = 512
    d_inner_hid = 2048
    d_key = 64
    d_value = 64
    n_head = 8
    n_layer = 6
    dropout = 0.1
    # attention-weight dropout (reference uses hp.dropout here too; the
    # flash kernel path supports 0.0 only — set >0 to force the composed
    # softmax path with weight dropout)
    attention_dropout = 0.0
    use_flash = True


def position_encoding_init(n_position, d_model):
    """Sinusoid position encoding table."""
    position = np.arange(n_position)[:, None].astype("float64")
    div = np.exp(np.arange(0, d_model, 2).astype("float64")
                 * -(np.log(10000.0) / d_model))
    table = np.zeros((n_position, d_model))
    table[:, 0::2] = np.sin(position * div)
    table[:, 1::2] = np.cos(position * div[: d_model // 2])
    return table.astype("float32")


def _shared_padding_bias(k_mask):
    """[B,S] mask -> [B,1,1,S] additive bias, built ONCE per mask var
    (layers share the constant instead of re-emitting it)."""
    name = k_mask.name + "@attn_bias"
    block = k_mask.block
    if block.has_var(name) and any(name in op.output_arg_names
                                   for op in block.ops):
        return block.var(name)
    neg = layers.scale(k_mask, scale=1e9, bias=-1e9)
    b, sk = k_mask.shape
    out = layers.reshape(neg, shape=[b, 1, 1, sk])
    block.vars.pop(out.name, None)
    out.name = name
    block.vars[name] = out
    block.ops[-1].outputs["Out"] = [name]
    return out


def _shared_causal_bias(block, sq):
    """[1,1,S,S] causal constant, one copy per program per length."""
    name = f"@causal_bias_{sq}"
    if block.has_var(name):
        return block.var(name)
    tri = np.triu(np.full((sq, sq), -1e9, dtype="float32"), 1)
    out = layers.assign(tri.reshape(1, 1, sq, sq))
    block.vars.pop(out.name, None)
    out.name = name
    block.vars[name] = out
    block.ops[-1].outputs["Out"] = [name]
    return out


def multi_head_attention(queries, keys, values, d_key, d_value, d_model,
                         n_head=1, dropout_rate=0.0, k_mask=None,
                         causal=False, use_flash=True, prefix=None):
    """Multi-head scaled-dot-product attention over dense [B,S,D] tensors.

    ``k_mask`` [B, S_k] (1=attend) covers padding; ``causal`` covers the
    decoder self-attention triangle.  With ``use_flash`` the fused Pallas
    kernel runs QK^T->softmax->AV in VMEM (no [B,H,S,S] HBM tensor); the
    flash path applies no attention-weight dropout — the composed-op path
    is used instead when attention dropout is requested.
    """
    keys = queries if keys is None else keys
    values = keys if values is None else values

    def pa(role):
        # structured names let tensor-parallel sharding rules (tp_shardings)
        # address parameters by role
        return ParamAttr(name=f"{prefix}_{role}.w") if prefix else None

    q = layers.fc(queries, d_key * n_head, num_flatten_dims=2,
                  bias_attr=False, param_attr=pa("q"))
    k = layers.fc(keys, d_key * n_head, num_flatten_dims=2, bias_attr=False,
                  param_attr=pa("k"))
    v = layers.fc(values, d_value * n_head, num_flatten_dims=2,
                  bias_attr=False, param_attr=pa("v"))

    def split_heads(x, d_per_head):
        # [B, S, H*D] -> [B, H, S, D]
        b, s = x.shape[0], x.shape[1]
        x = layers.reshape(x, shape=[b, s, n_head, d_per_head])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    q = split_heads(q, d_key)
    k = split_heads(k, d_key)
    v = split_heads(v, d_value)
    scale = float(d_key) ** -0.5

    # the VMEM-fused kernel wins once the [S,S] score tensor dominates HBM
    # traffic; crossover is workload-dependent, so the threshold is a knob
    # (PADDLE_TPU_FLASH_MIN_S; default 512 = the measured v5e DEVICE-time
    # crossover, BENCH_ATTENTION.md r4: S=256 flash 0.73x of composed,
    # S=512 1.42x, S=2048 2.77x, S=4096 composed OOMs).  At S=256 the
    # composed path also wins IN-MODEL for extra reasons (bench A/B +
    # per-op profile): the pallas custom call pins a [B,H,S,D] layout
    # costing ~15ms/step of HBM transposes which XLA otherwise folds
    # into the projection matmuls, and the call boundary splits fusion
    # clusters (~11ms) — at D=64, QK^T can at best half-fill the MXU's
    # 128-deep systolic array while the [S,S] round-trip is still cheap.
    import os
    flash_min_s = int(os.environ.get("PADDLE_TPU_FLASH_MIN_S", "512"))
    use_flash = use_flash and (k.shape[2] >= flash_min_s)
    # sequence/context parallelism: shard S over the mesh 'seq' axis and
    # attend with the ppermute ring (parallel/ring_attention.py); only for
    # self-attention (q and k share the sequence sharding)
    from paddle_tpu.executor import _env_flag
    seq_parallel = _env_flag("PADDLE_TPU_SEQ_PARALLEL") and \
        keys is queries and k_mask is None

    if seq_parallel and not dropout_rate:
        ctx = layers.ring_attention(q, k, v, causal=causal, scale=scale)
    elif use_flash and not dropout_rate:
        ctx = layers.fused_attention(q, k, v, k_mask=k_mask, causal=causal,
                                     scale=scale)
    else:
        product = layers.matmul(q, k, transpose_y=True, alpha=scale)
        # fold the mask into the softmax op: under bf16 AMP the [B,H,S,S]
        # scores then stay bf16 in HBM (an f32 add would otherwise promote
        # and double the attention hot spot's traffic); softmax itself
        # computes in f32 internally
        bias = None
        if k_mask is not None:
            bias = _shared_padding_bias(k_mask)
        if causal:
            cb = _shared_causal_bias(q.block, q.shape[2])
            bias = cb if bias is None else bias + cb
        weights = layers.softmax(product, bias=bias)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate)
        ctx = layers.matmul(weights, v)

    # [B, H, S, D] -> [B, S, H*D]
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    b, s = ctx.shape[0], ctx.shape[1]
    ctx = layers.reshape(ctx, shape=[b, s, n_head * d_value])
    return layers.fc(ctx, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=pa("attnout"))


def positionwise_feed_forward(x, d_inner_hid, d_hid, prefix=None):
    def pa(role, suffix="w"):
        return ParamAttr(name=f"{prefix}_{role}.{suffix}") if prefix \
            else None
    hidden = layers.fc(x, d_inner_hid, num_flatten_dims=2, act="relu",
                       param_attr=pa("ffn1"),
                       bias_attr=pa("ffn1", "b"))
    return layers.fc(hidden, d_hid, num_flatten_dims=2,
                     param_attr=pa("ffn2"),
                     bias_attr=pa("ffn2", "b"))


def pre_post_process_layer(prev_out, out, process_cmd, dropout_rate=0.0):
    for cmd in process_cmd:
        if cmd == "a":
            out = out + prev_out if prev_out is not None else out
        elif cmd == "n":
            out = layers.layer_norm(
                out, begin_norm_axis=len(out.shape) - 1,
                param_attr=ParamAttr(initializer=None),
                bias_attr=ParamAttr(initializer=None))
        elif cmd == "d" and dropout_rate:
            out = layers.dropout(out, dropout_prob=dropout_rate)
    return out


def encoder_layer(enc_input, src_mask, hp: ModelHyperParams, idx=0):
    attn = multi_head_attention(enc_input, None, None,
                                hp.d_key, hp.d_value, hp.d_model,
                                hp.n_head, hp.attention_dropout,
                                k_mask=src_mask, use_flash=hp.use_flash,
                                prefix=f"enc{idx}_attn")
    attn = pre_post_process_layer(enc_input, attn, "dan", hp.dropout)
    ffd = positionwise_feed_forward(attn, hp.d_inner_hid, hp.d_model,
                                    prefix=f"enc{idx}")
    return pre_post_process_layer(attn, ffd, "dan", hp.dropout)


def decoder_layer(dec_input, enc_output, src_mask, hp: ModelHyperParams,
                  idx=0):
    self_attn = multi_head_attention(dec_input, None, None,
                                     hp.d_key, hp.d_value, hp.d_model,
                                     hp.n_head, hp.attention_dropout,
                                     causal=True, use_flash=hp.use_flash,
                                     prefix=f"dec{idx}_self")
    self_attn = pre_post_process_layer(dec_input, self_attn, "dan",
                                       hp.dropout)
    cross = multi_head_attention(self_attn, enc_output, enc_output,
                                 hp.d_key, hp.d_value, hp.d_model,
                                 hp.n_head, hp.attention_dropout,
                                 k_mask=src_mask, use_flash=hp.use_flash,
                                 prefix=f"dec{idx}_cross")
    cross = pre_post_process_layer(self_attn, cross, "dan", hp.dropout)
    ffd = positionwise_feed_forward(cross, hp.d_inner_hid, hp.d_model,
                                    prefix=f"dec{idx}")
    return pre_post_process_layer(cross, ffd, "dan", hp.dropout)


def prepare_embedding(ids, pos_ids, vocab_size, hp: ModelHyperParams,
                      name_prefix):
    word_emb = layers.embedding(
        ids, size=[vocab_size, hp.d_model],
        param_attr=ParamAttr(name=name_prefix + "_word_emb"))
    word_emb = layers.scale(word_emb, scale=float(hp.d_model) ** 0.5)
    pos_table = position_encoding_init(hp.max_length, hp.d_model)
    pos_emb = layers.embedding(
        pos_ids, size=[hp.max_length, hp.d_model],
        param_attr=ParamAttr(
            name=name_prefix + "_pos_emb", trainable=False,
            initializer=NumpyArrayInitializer(pos_table)))
    out = word_emb + pos_emb
    if hp.dropout:
        out = layers.dropout(out, dropout_prob=hp.dropout)
    return out


def encoder(src_ids, src_pos, src_mask, hp: ModelHyperParams):
    x = prepare_embedding(src_ids, src_pos, hp.src_vocab_size, hp, "src")
    for i in range(hp.n_layer):
        x = encoder_layer(x, src_mask, hp, idx=i)
    return x


def decoder(trg_ids, trg_pos, enc_output, src_mask, hp: ModelHyperParams):
    x = prepare_embedding(trg_ids, trg_pos, hp.trg_vocab_size, hp, "trg")
    for i in range(hp.n_layer):
        x = decoder_layer(x, enc_output, src_mask, hp, idx=i)
    return x


def build_inputs(batch_size, src_len, trg_len, hp: ModelHyperParams):
    """Declare the dense feed variables.

    Host→device traffic is the TPU bottleneck (feeds may cross DCN), so
    only ids and [B, S] masks are fed; position ids and the [B,1,S,S]
    additive attention biases are built IN-GRAPH as constants/cheap
    broadcasts (unlike the reference benchmark which feeds dense
    [B, n_head, S, S] bias tensors).
    """
    def data(name, shape, dtype):
        return layers.data(name=name, shape=shape, dtype=dtype,
                           append_batch_size=False)

    src_ids = data("src_word", [batch_size, src_len], "int32")
    trg_ids = data("trg_word", [batch_size, trg_len], "int32")
    src_mask = data("src_mask", [batch_size, src_len], "float32")
    labels = data("lbl_word", [batch_size, trg_len], "int32")
    weights = data("lbl_weight", [batch_size, trg_len], "float32")
    return src_ids, trg_ids, src_mask, labels, weights


def _position_ids(batch_size, seq_len):
    """Constant [B, S] int32 position-id tensor (in-graph)."""
    pos = np.tile(np.arange(seq_len, dtype="int32"), (batch_size, 1))
    return layers.assign(pos)


def transformer(batch_size, src_len, trg_len, hp: ModelHyperParams = None,
                input_vars=None):
    """Build the full training graph; returns (avg_cost, feed_vars).

    ``input_vars``: optional 5-tuple (src_ids, trg_ids, src_mask, labels,
    weights) of pre-built variables — e.g. ``layers.read_file`` outputs of
    a recordio reader pipeline — replacing the dense feed declarations.
    """
    hp = hp or ModelHyperParams()
    if input_vars is not None:
        src_ids, trg_ids, src_mask, labels, weights = input_vars
    else:
        src_ids, trg_ids, src_mask, labels, weights = build_inputs(
            batch_size, src_len, trg_len, hp)

    src_pos = _position_ids(batch_size, src_len)
    trg_pos = _position_ids(batch_size, trg_len)

    enc_out = encoder(src_ids, src_pos, src_mask, hp)
    dec_out = decoder(trg_ids, trg_pos, enc_out, src_mask, hp)

    logits = layers.fc(dec_out, hp.trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=ParamAttr(name="proj_logits.w"))
    logits2d = layers.reshape(
        logits, shape=[batch_size * trg_len, hp.trg_vocab_size])
    labels2d = layers.reshape(labels, shape=[batch_size * trg_len, 1])
    cost = layers.softmax_with_cross_entropy(logits2d, labels2d)
    weights2d = layers.reshape(weights, shape=[batch_size * trg_len, 1])
    weighted = cost * weights2d
    sum_cost = layers.reduce_sum(weighted)
    token_count = layers.reduce_sum(weights2d)
    avg_cost = sum_cost / token_count
    feeds = ["src_word", "trg_word", "src_mask", "lbl_word", "lbl_weight"]
    return avg_cost, feeds


def fake_batch(batch_size, src_len, trg_len, hp: ModelHyperParams = None,
               seed=0):
    """Synthetic dense batch for benchmarking/compile checks."""
    hp = hp or ModelHyperParams()
    rng = np.random.RandomState(seed)
    src_word = rng.randint(1, hp.src_vocab_size,
                           size=(batch_size, src_len)).astype("int32")
    trg_word = rng.randint(1, hp.trg_vocab_size,
                           size=(batch_size, trg_len)).astype("int32")
    src_mask = np.ones((batch_size, src_len), dtype="float32")
    lbl_word = rng.randint(1, hp.trg_vocab_size,
                           size=(batch_size, trg_len)).astype("int32")
    lbl_weight = np.ones((batch_size, trg_len), dtype="float32")
    return {
        "src_word": src_word, "trg_word": trg_word, "src_mask": src_mask,
        "lbl_word": lbl_word, "lbl_weight": lbl_weight,
    }


def param_count(hp: ModelHyperParams = None):
    """Approximate dense parameter count: the matmul params plus the
    embedding tables and the per-layer layernorm scale/bias terms
    (2 layernorms/encoder layer, 3/decoder layer, 2 params each of
    width d)."""
    hp = hp or ModelHyperParams()
    d = hp.d_model
    emb = (hp.src_vocab_size + hp.trg_vocab_size) * d
    layernorm = hp.n_layer * (4 * d + 6 * d)
    return matmul_param_count(hp) + emb + layernorm


def matmul_param_count(hp: ModelHyperParams = None):
    """Parameters that participate in matmuls — the honest basis for the
    6N-FLOPs/token MFU estimate.  Excludes the input embedding tables
    (their forward is a gather, not a matmul; their backward is a
    scatter-add) and the layernorm scale/bias terms (elementwise), but
    includes the output projection, which IS a matmul.
    """
    hp = hp or ModelHyperParams()
    d, dff = hp.d_model, hp.d_inner_hid
    per_enc = 4 * d * d + 2 * d * dff
    per_dec = 8 * d * d + 2 * d * dff
    proj = d * hp.trg_vocab_size
    return hp.n_layer * (per_enc + per_dec) + proj


def train_flops_per_token(hp: ModelHyperParams = None, seq=None):
    """Analytical training FLOPs per (target) token — the 6N-matmul +
    attention accounting ``bench.py`` derives MFU from:

    * ``6 * matmul_param_count`` — fwd (2N) + bwd (4N) per matmul
      parameter; input embeddings excluded (gather, not matmul), the
      output projection included.
    * attention: 3 modules/layer (enc-self, dec-self, cross), each
      QK^T + AV = ``4*S*d`` FLOPs/token fwd, bwd 2x => ``12*S*d``.

    The cross-check test (``tests/test_perf.py``) holds this against
    the XLA ``cost_analysis()`` FLOPs of the compiled train step within
    a declared band, so drift in the hand accounting MFU claims rest on
    cannot land silently."""
    hp = hp or ModelHyperParams()
    seq = seq if seq is not None else hp.max_length
    attn_flops = 12 * seq * hp.d_model * (3 * hp.n_layer)
    return 6 * matmul_param_count(hp) + attn_flops


def tp_shardings():
    """Megatron-style tensor-parallel PartitionSpec rules for the model's
    parameters (and, by substring match, their Adam moments) over a mesh
    with a ``model`` axis.  Pass to
    ``ParallelExecutor(param_shardings=...)``; GSPMD inserts the
    collectives (replacing the reference's explicit pserver/NCCL plumbing,
    SURVEY.md §2.8)."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"_(q|k|v)\.w", P(None, "model")),         # column parallel
        (r"_attnout\.w", P("model", None)),         # row parallel
        (r"_ffn1\.w", P(None, "model")),
        (r"_ffn1\.b", P("model")),                  # bias [FF]
        (r"_ffn2\.w", P("model", None)),
        (r"(src|trg)_word_emb", P(None, "model")),  # shard d_model
        (r"proj_logits\.w", P(None, "model")),      # shard vocab
    ]
