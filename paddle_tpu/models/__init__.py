"""Model zoo: program-builder functions for the benchmark workloads the
reference ships under ``benchmark/fluid/`` (mnist, resnet, vgg,
machine_translation/transformer, stacked_dynamic_lstm) — re-built on the
TPU-native layers API."""

from paddle_tpu.models import (resnet, transformer, vgg, mnist,
                               seq2seq, stacked_lstm)

__all__ = ["resnet", "transformer", "vgg", "mnist",
           "seq2seq", "stacked_lstm"]
