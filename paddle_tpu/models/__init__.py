"""Model zoo: program-builder functions for the benchmark workloads the
reference ships under ``benchmark/fluid/`` (mnist, resnet, vgg,
machine_translation/transformer, stacked_dynamic_lstm) — re-built on the
TPU-native layers API."""

from paddle_tpu.models import (resnet, transformer, vgg, mnist,
                               seq2seq, stacked_lstm, gen_lm,
                               gen_lm_long, wide_and_deep)

__all__ = ["resnet", "transformer", "vgg", "mnist",
           "seq2seq", "stacked_lstm", "gen_lm", "gen_lm_long",
           "wide_and_deep", "ZOO_MODELS",
           "build_train_program", "synth_feed", "compile_zoo_step"]

#: zoo model names accepted by :func:`build_train_program` (and by
#: ``paddle_tpu lint --zoo``; the lint gate in
#: tests/test_analysis_zoo.py iterates exactly this list)
ZOO_MODELS = ("mnist", "resnet", "vgg", "transformer", "seq2seq",
              "stacked_lstm", "gen_lm", "gen_lm_long", "wide_and_deep")


def build_train_program(name, backward=True):
    """Build one zoo model's forward(+backward+optimizer) program with
    small smoke-test dimensions.

    Returns ``(main_program, startup_program, feed_names, fetch_names)``
    — ``feed_names`` is None when the model builds its own feed vars
    (the analyzer then infers them from ``is_data``).  Shared by
    ``paddle_tpu lint --zoo`` and the model-zoo lint gate so the CLI and
    CI analyze the same programs.
    """
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if name == "mnist":
            cost, acc, feeds = mnist.mnist_train_program(8)
            fetches = [cost.name, acc.name]
        elif name == "resnet":
            cost, acc, feeds = resnet.resnet_train_program(
                2, class_dim=10, depth=18, image_shape=(3, 32, 32))
            fetches = [cost.name, acc.name]
        elif name == "vgg":
            cost, acc, feeds = vgg.vgg_train_program(2, class_dim=10)
            fetches = [cost.name, acc.name]
        elif name == "transformer":
            hp = transformer.ModelHyperParams()
            hp.d_model, hp.d_inner_hid, hp.n_layer, hp.n_head = 32, 64, 1, 2
            hp.d_key = hp.d_value = 16
            hp.src_vocab_size = hp.trg_vocab_size = 64
            hp.max_length = 16
            cost, _ = transformer.transformer(2, 8, 8, hp)
            feeds, fetches = None, [cost.name]
        elif name == "seq2seq":
            cost, _ = seq2seq.seq_to_seq_net(
                16, 16, emb_dim=8, encoder_size=8, decoder_size=8)
            feeds, fetches = None, [cost.name]
        elif name == "stacked_lstm":
            cost, acc, _ = stacked_lstm.stacked_lstm_net(
                dict_size=16, emb_dim=8, hidden_dim=8, n_layers=2)
            feeds, fetches = None, [cost.name, acc.name]
        elif name == "gen_lm":
            hp = gen_lm.GenConfig()
            hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
            hp.n_head = hp.n_layer = 2
            hp.d_head, hp.max_len = 8, 16
            cost, feeds = gen_lm.gen_lm_train_program(2, 8, hp)
            fetches = [cost.name]
        elif name == "wide_and_deep":
            cost, acc, feeds = wide_and_deep.wide_and_deep_train_program(
                4, vocab_size=16, num_slots=2, emb_dim=4, dense_dim=4,
                hidden=8)
            fetches = [cost.name, acc.name]
        elif name == "gen_lm_long":
            # flagship long-context geometry: max_len stays at the
            # GenLongConfig 256 (the gated axis); the rest shrinks to
            # smoke-test scale like the base gen_lm entry
            hp = gen_lm_long.GenLongConfig()
            hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
            hp.n_head = hp.n_layer = 2
            hp.d_head = 8
            cost, feeds = gen_lm_long.gen_lm_long_train_program(2, 16, hp)
            fetches = [cost.name]
        else:
            raise ValueError(
                f"unknown zoo model {name!r}; expected one of "
                f"{ZOO_MODELS}")
        if backward:
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
    return main, startup, feeds, fetches


def synth_feed(main_program, feed_names=None, batch=2):
    """Synthetic (zero-filled) feed dict for a zoo main program — what
    ``paddle_tpu profile compile|memory`` and the selfcheck ``perf``
    section execute one step with to force a real compile without a
    dataset.  Zeros are valid everywhere the zoo reads labels or token
    ids (class/token 0 exists); dynamic dims synthesize as ``batch``.
    ``feed_names=None`` falls back to the program's ``is_data`` vars
    (models that build their own feed layers)."""
    block = main_program.global_block()
    if feed_names is None:
        feed_names = [v.name for v in block.vars.values()
                      if getattr(v, "is_data", False)]
    from paddle_tpu.io import synth_feed_value

    feed = {}
    for name in feed_names:
        var = block.var(name)
        shape = tuple(batch if d is None or int(d) < 0 else int(d)
                      for d in (var.shape or (batch,)))
        feed[name] = synth_feed_value(shape, var.dtype or "float32")
    return feed


def compile_zoo_step(name, batch=2):
    """Fresh-compile one zoo model: build, run startup, run ONE
    synthetic train step in a fresh scope — the shared recipe
    ``paddle_tpu profile compile|memory`` and selfcheck's ``perf``
    section use to force a real captured compile without a dataset.
    Returns the scope (for a following HBM census)."""
    import paddle_tpu as fluid

    main, startup, feeds, fetches = build_train_program(name)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=synth_feed(main, feeds, batch=batch),
                fetch_list=fetches, scope=scope)
    return scope
