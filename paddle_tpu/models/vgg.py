"""VGG-16 (reference ``benchmark/fluid/vgg.py`` — the cluster benchmark
workload, BASELINE.md distributed tables)."""

from __future__ import annotations

import paddle_tpu.layers as layers
import paddle_tpu.nets as nets


def vgg16_bn_drop(input):
    def conv_block(ipt, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return fc2


def vgg_train_program(batch_size, class_dim=10, image_shape=(3, 32, 32)):
    image = layers.data(name="image", shape=[batch_size] + list(image_shape),
                        dtype="float32", append_batch_size=False)
    label = layers.data(name="label", shape=[batch_size, 1], dtype="int64",
                        append_batch_size=False)
    net = vgg16_bn_drop(image)
    predict = layers.fc(input=net, size=class_dim, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(x=cost)
    acc = layers.accuracy(input=predict, label=label)
    return avg_cost, acc, ["image", "label"]
