"""Gradient clipping (reference ``python/paddle/fluid/clip.py``:
GradientClipByValue / ByNorm / ByGlobalNorm + error-clip hooks)."""

from __future__ import annotations

from paddle_tpu import framework
from paddle_tpu.framework import unique_name

__all__ = ["ErrorClipByValue", "GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "append_gradient_clip_ops",
           "error_clip_callback", "set_gradient_clip"]


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def append_clip_op(self, block, grad_name):
        block.append_op(type="clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max})


def error_clip_callback(block, op):
    # reference clip.py error_clip_callback: clip activation grads per var
    for grad_n in op.output_arg_names if hasattr(op, "output_arg_names") \
            else []:
        if not grad_n.endswith(framework.GRAD_SUFFIX):
            continue
        fwd_var_name = grad_n[:-len(framework.GRAD_SUFFIX)]
        try:
            fwd_var = block.var(fwd_var_name)
        except KeyError:
            continue
        error_clip = getattr(fwd_var, "error_clip", None)
        if error_clip is not None:
            error_clip.append_clip_op(block, grad_n)


class BaseGradientClipAttr:
    def process_context(self, context, param, grad):
        raise NotImplementedError

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        from paddle_tpu.layers import nn
        new_grad = nn.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        from paddle_tpu.layers import nn
        new_grad = nn.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        elif context[self.group_name + "_clip_value"] != self.clip_norm:
            raise ValueError("all parameters in a group should share the "
                             "same clip norm")
        from paddle_tpu.layers import nn
        block = grad.block
        sq = block.create_var(dtype=grad.dtype, shape=(1,))
        block.append_op(type="squared_l2_norm", inputs={"X": [grad]},
                        outputs={"Out": [sq]})
        context[self.group_name].append(sq)
        self.context = context

    def create_operators(self, param, grad):
        from paddle_tpu.layers import nn, tensor, ops
        group_scale_name = self.group_name + "_scale"
        if group_scale_name not in self.context:
            group_norm_var = tensor.sums(self.context[self.group_name])
            group_norm_var = ops.sqrt(group_norm_var)
            clip_var = tensor.fill_constant([1], group_norm_var.dtype,
                                            self.clip_norm)
            group_scale_var = nn.elementwise_div(
                x=clip_var,
                y=nn.elementwise_max(x=clip_var, y=group_norm_var))
            self.context[group_scale_name] = group_scale_var
        new_grad = nn.elementwise_mul(x=grad,
                                      y=self.context[group_scale_name])
        return param, new_grad


def set_gradient_clip(clip, param_list=None, program=None):
    from paddle_tpu.framework import default_main_program
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError("clip should be an instance of BaseGradientClipAttr")
    program = program or default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [program.global_block().var(p) if isinstance(p, str) else p
                  for p in param_list]
    for param in param_list:
        param.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = {}
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or \
            NullGradientClipAttr()
        clip_attr.process_context(context=context, param=p, grad=g)
    res = []
    for p, g in param_grad:
        clip_attr = getattr(p, "gradient_clip_attr", None) or \
            NullGradientClipAttr()
        res.append(clip_attr.create_operators(param=p, grad=g))
    return res


ClipByValue = GradientClipByValue
