"""IR-level pipeline partitioning: split a Program's op list into P
balanced stages and run it as a GPipe pipeline (VERDICT r3 item 3 —
completes ``parallel/pipeline.py``'s primitive into a framework feature).

The reference has no pipeline parallelism; SURVEY.md §2.8 names PP as a
beyond-reference row.  Design:

* ``split_program``: walk the global block's ops in program order,
  weight them with the same analytic FLOP model the benchmarks use
  (conv/matmul dominate), and cut at the P-quantiles of cumulative
  cost.  Any cut is legal: everything produced before the cut and
  consumed after it becomes part of the boundary *carrier*.  Ops that
  carry sub-blocks (while/cond/DynamicRNN) are atomic — they are never
  split across a cut, and their lowerings recurse into their sub-block
  the same way the executor's ``lower_block`` does.
* Stages are NON-homogeneous (different ops, params, shapes).  Each
  stage's parameters are flat-packed into TYPED LANES — one flat vector
  per dtype class (``f32``, ``bf16``, ``i32``) — padded to a common
  per-lane length and stacked [P, L_lane], sharded over the ``pipe``
  mesh axis so each device stores only its own stage's weights.  Inside
  ``shard_map`` a ``lax.switch`` on the device's stage index unpacks
  its slices and runs its stage's traced IR ops.
* Activations/feeds cross boundaries the same way: one flat carrier per
  lane of uniform (max-boundary) length.  Integer values ride the i32
  lane EXACTLY (the r4 design packed them as f32, silently rounding
  ids >= 2^24; host-side int64 values beyond int32 range are rejected
  loudly rather than wrapped); bf16 values keep bf16 width on the
  wire; floats ride f32.  Lanes that no boundary/parameter uses are dropped from the
  pytree, so ``jax.grad`` over the packed params needs ``allow_int``
  only when an integer parameter actually exists.
* Microbatches feed STAGE 0 ONLY (the refinement pipeline.py:70-73
  names): the per-lane [M, L] ingest tensors are sharded over ``pipe``
  in contiguous blocks of B = M/P; after every B ticks the local blocks
  rotate one hop toward stage 0 on the ICI ring, arriving exactly when
  stage 0 needs them — devices never hold the full microbatch set.
* The whole schedule is differentiable: ``jax.grad`` w.r.t. the packed
  lane dict yields the reverse pipeline, and ``unpack_grads`` scatters
  it back to named parameters (parameters used by several stages get
  their contributions summed).
* AMP: the stage branches honor the program's mixed-precision flag
  (``Program.amp``), so a bf16-AMP program pipelines with the same op-
  level cast discipline as the executor.  A boundary cut inserts an
  exact bf16→f32→bf16 round-trip for values that are bf16 at runtime
  (value-preserving; see test_pipeline_transpiler.py AMP parity).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import Parameter

try:
    from jax import shard_map
    _SM_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
    _SM_CHECK_KW = "check_rep"

__all__ = ["pipeline_transpiler", "PipelinedProgram"]

_SKIP = ("feed", "fetch")

_LANE_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "i32": jnp.int32}


def _lane_of(dtype):
    """Which carrier lane a dtype rides: bf16 keeps its width, other
    floats ride f32 (f16 upcast losslessly; f64 is already f32 under
    JAX's default x64-off), ints/bools ride i32 exactly."""
    name = str(np.dtype(dtype).name) if not isinstance(dtype, str) \
        else dtype
    if name == "bfloat16":
        return "bf16"
    if name.startswith("float"):
        return "f32"
    return "i32"


def _np_dtype(dtype):
    """np dtype for restore; 'bfloat16' restores via jnp."""
    if str(dtype) == "bfloat16":
        return jnp.bfloat16
    return np.dtype(dtype)


def _op_cost(op, block):
    """Per-op stage-balancing weight: the shared static cost model
    (``analysis/cost.op_flops`` — the same per-op rules the optimizer
    pipeline and GenScheduler admission ride, replacing this module's
    former private three-op table, so the accountings can't drift).
    Sub-block ops (while/cond/DynamicRNN) are atomic: weighed by their
    body so the quantile cuts see the FLOPs inside."""
    from paddle_tpu.analysis import cost as _cost
    flops = _cost.op_flops(op, block, default=0)
    inner = sum(_op_cost(sub, blk)
                for blk in _sub_blocks(op) for sub in blk.ops)
    return 1 + flops + inner


def _all_input_names(op, recurse=False):
    names = [n for vs in op.inputs.values() for n in vs]
    if recurse:
        for blk in _sub_blocks(op):
            for sub in blk.ops:
                names += _all_input_names(sub, recurse=True)
    return names


def _all_output_names(op, recurse=False):
    names = [n for vs in op.outputs.values() for n in vs]
    if recurse:
        for blk in _sub_blocks(op):
            for sub in blk.ops:
                names += _all_output_names(sub, recurse=True)
    return names


def _sub_blocks(op):
    return [a for a in op.attrs.values()
            if a.__class__.__name__ == "Block"]


def split_program(program, n_stages, feed_names, fetch_names):
    """Balanced cut points + per-stage op/param/boundary metadata."""
    block = program.global_block()
    ops = [op for op in block.ops if op.type not in _SKIP]

    costs = [_op_cost(op, block) for op in ops]
    total = float(sum(costs))
    # cut after reaching each quantile of cumulative cost
    cuts, acc, next_q = [], 0.0, 1
    for i, c in enumerate(costs):
        acc += c
        if next_q < n_stages and acc >= total * next_q / n_stages:
            cuts.append(i + 1)
            next_q += 1
    while len(cuts) < n_stages - 1:   # degenerate tails
        cuts.append(len(ops))
    stage_ops = []
    lo = 0
    for cut in cuts + [len(ops)]:
        stage_ops.append(ops[lo:cut])
        lo = cut

    def is_param(name):
        v = block.var(name) if name in block.vars else None
        return v is not None and (isinstance(v, Parameter)
                                  or getattr(v, "persistable", False))

    produced_by = {}
    for s, sops in enumerate(stage_ops):
        for op in sops:
            for n in _all_output_names(op):
                produced_by.setdefault(n, s)

    # sub-block ops are atomic; their inner reads of outer params/vars
    # count toward the owning stage (recurse=True)
    stage_params = []
    for sops in stage_ops:
        names = []
        for op in sops:
            for n in _all_input_names(op, recurse=True):
                if is_param(n) and n not in names:
                    names.append(n)
        stage_params.append(names)

    # boundary b carries everything still needed past it and produced
    # before it: inputs of stage >= b ops, plus fetch targets already
    # produced (they must ride through to the final boundary); feeds
    # count as produced before stage 0
    feed_set = set(feed_names)
    boundaries = []
    for b in range(n_stages + 1):
        need = set()
        for n in fetch_names:
            src = produced_by.get(n)
            # a fetched feed (src None) must ride EVERY boundary — no
            # stage re-produces it, wherever its consumers sit
            if b == n_stages or (src is not None and src < b) or \
                    (src is None and n in feed_set):
                need.add(n)
        for s in range(b, n_stages):
            for op in stage_ops[s]:
                for n in _all_input_names(op, recurse=True):
                    if is_param(n):
                        continue
                    src = produced_by.get(n)
                    if (src is None and n in feed_set) or \
                            (src is not None and src < b):
                        need.add(n)
        boundaries.append(sorted(need))

    # carriers are flat dense vectors; a TensorArray (or reader/channel)
    # cannot cross a cut.  The cut placement is cost-driven, so reject
    # loudly with the remedy instead of crashing in _Layout.pack.
    for b, names in enumerate(boundaries):
        for n in names:
            v = block.var(n) if n in block.vars else None
            vtype = getattr(v, "type", None)
            if vtype in ("tensor_array", "reader", "channel"):
                where = ("the feed carrier" if b == 0 else
                         "the fetch carrier" if b == len(boundaries) - 1
                         else f"the cut before stage {b}")
                raise ValueError(
                    f"pipeline_transpiler: {where} would carry {n!r} "
                    f"(a {vtype}), which cannot ride a flat carrier; "
                    f"keep its producers and consumers in one stage "
                    f"and fetch/feed dense tensors only — fewer "
                    f"stages, or hoist the control-flow region so the "
                    f"quantile cut lands outside it")
    return block, stage_ops, stage_params, boundaries


class _Layout:
    """Typed flat-packing layout for a list of named tensors: one flat
    vector per dtype lane (f32 / bf16 / i32); ``pack`` -> {lane: vec},
    ``unpack`` restores original dtypes/shapes."""

    def __init__(self, names, shapes, dtypes):
        self.names = list(names)
        self.shapes = [tuple(s) for s in shapes]
        self.dtypes = list(dtypes)
        self.lanes = [_lane_of(d) for d in self.dtypes]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = []          # per-name offset within its lane
        self.lengths = {}          # lane -> total length
        for lane, size in zip(self.lanes, self.sizes):
            self.offsets.append(self.lengths.get(lane, 0))
            self.lengths[lane] = self.lengths.get(lane, 0) + size

    @staticmethod
    def _check_i32_range(name, v):
        """Range-check any CONCRETE int64-typed value before it rides
        the i32 lane — a >= 2^31 id must fail loudly, not wrap.  Keyed
        on the value's DTYPE, not ``isinstance(np.ndarray)``: numpy
        scalars and x64-enabled jax arrays are int64-typed without
        being ndarrays, and must not bypass the guard (ADVICE r5).
        Abstract tracers are exempt: they cannot be concretized, and
        under JAX's default x64-off no tracer is int64 anyway."""
        dt = getattr(v, "dtype", None)
        if dt is None or np.dtype(dt) != np.int64 \
                or isinstance(v, jax.core.Tracer):
            return
        a = np.asarray(v)
        if a.size and (a.max() > np.iinfo(np.int32).max or
                       a.min() < np.iinfo(np.int32).min):
            raise ValueError(
                f"pipeline_transpiler: {name!r} holds int64 values "
                f"outside int32 range; the i32 carrier lane cannot "
                f"carry them exactly")

    def pack(self, values, lanes):
        """values {name: array} -> {lane: flat vec} over ``lanes``;
        int64 values are range-guarded by :meth:`_check_i32_range`
        (the static half of the same contract is the analyzer's PTA010
        int64-lane lint, ``analysis.check_pipeline_carriers``)."""
        flats = {lane: [] for lane in lanes}
        for n, lane in zip(self.names, self.lanes):
            v = values[n]
            if lane == "i32":
                self._check_i32_range(n, v)
            flats[lane].append(
                jnp.ravel(v).astype(_LANE_DTYPES[lane]))
        return {
            lane: (jnp.concatenate(fs) if fs
                   else jnp.zeros((0,), _LANE_DTYPES[lane]))
            for lane, fs in flats.items()}

    def unpack(self, vecs):
        """{lane: vec} -> {name: array} with original dtype/shape."""
        out = {}
        for n, shape, dtype, lane, off, size in zip(
                self.names, self.shapes, self.dtypes, self.lanes,
                self.offsets, self.sizes):
            out[n] = jax.lax.slice(vecs[lane], (off,), (off + size,)) \
                .reshape(shape).astype(_np_dtype(dtype))
        return out


def _pad_lanes(vecs, lengths):
    return {
        lane: (jnp.pad(v, (0, lengths[lane] - v.shape[0]))
               if v.shape[0] < lengths[lane] else v)
        for lane, v in vecs.items()}


class PipelinedProgram:
    """A Program split into P pipeline stages; call :meth:`run_fn` (or
    differentiate through it) with per-microbatch feeds."""

    def __init__(self, program, n_stages, feed_names, fetch_names, mesh,
                 axis="pipe"):
        from paddle_tpu.ops import registry as _registry
        from paddle_tpu.executor import _amp_enabled
        self._registry = _registry
        self.mesh = mesh
        self.axis = axis
        self.n_stages = n_stages
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.amp = _amp_enabled(program)
        # post-transpile contract: the program must be structurally
        # well-formed BEFORE it is cut into stages (a bad rewrite fails
        # here, named, instead of inside the shard_map trace), and no
        # int64 constant provably outside int32 range may cross a stage
        # boundary on the i32 carrier lane (the static half of
        # _Layout.pack's runtime range guard)
        from paddle_tpu.analysis import (AnalysisResult,
                                         check_pipeline_carriers,
                                         check_stage_set,
                                         verify_transpiled)
        verify_transpiled(program, where="pipeline_transpiler")
        (self.block, self.stage_ops, self.stage_param_names,
         self.boundaries) = split_program(program, n_stages, feed_names,
                                          fetch_names)
        check_pipeline_carriers(self.block, self.boundaries)
        # cross-stage contract (analysis/distributed.py): every consumed
        # upstream value rides its boundary carrier, and the stages —
        # run as lax.switch branches on the SAME devices — emit matching
        # collective sequences (a branch-local collective its peers
        # don't run would deadlock the mesh: PTA011/PTA015)
        AnalysisResult(check_stage_set(
            self.block, self.stage_ops, self.boundaries,
            feed_names=self.feed_names)) \
            .raise_on_errors(where="pipeline_transpiler")

        def check_rng(op):
            opdef = _registry.lookup(op.type)
            if opdef is not None and opdef.uses_rng:
                raise ValueError(
                    f"pipeline_transpiler: op {op.type!r} uses the "
                    f"rng stream; run with dropout/sampling disabled "
                    f"in the pipelined region")
            for blk in _sub_blocks(op):
                for sub in blk.ops:
                    check_rng(sub)

        for sops in self.stage_ops:
            for op in sops:
                check_rng(op)

    # -- layouts (need var shapes; resolved against scope values) -------
    def _var_meta(self, name, scope_vals):
        v = self.block.var(name) if name in self.block.vars else None
        if name in scope_vals:
            arr = np.asarray(scope_vals[name])
            return arr.shape, arr.dtype
        if v is None or v.shape is None:
            raise ValueError(f"pipeline_transpiler: no shape for {name!r}")
        shape = tuple(int(d) for d in v.shape)
        return shape, v.dtype

    def build(self, scope, microbatch_feeds):
        """Finalize layouts from the startup-initialized ``scope`` and a
        SAMPLE microbatch feed dict (fixes the microbatch shapes)."""
        sample = {k: np.asarray(v) for k, v in microbatch_feeds.items()}
        self._param_layouts = []
        param_values = []     # local: only needed to build packed_params
        for names in self.stage_param_names:
            vals = {n: np.asarray(scope.find_var(n)) for n in names}
            lay = _Layout(names, [vals[n].shape for n in names],
                          [vals[n].dtype for n in names])
            self._param_layouts.append(lay)
            param_values.append(vals)

        self._carrier_layouts = []
        for names in self.boundaries:
            shapes, dtypes = [], []
            for n in names:
                if n in sample:
                    shapes.append(sample[n].shape)
                    dtypes.append(sample[n].dtype)
                else:
                    s, d = self._var_meta(n, {})
                    shapes.append(s)
                    dtypes.append(d)
            self._carrier_layouts.append(_Layout(names, shapes, dtypes))

        # active lanes: fixed pytree structure across boundaries/stages
        self.carrier_lanes = tuple(
            lane for lane in _LANE_DTYPES
            if any(lay.lengths.get(lane) for lay in self._carrier_layouts))
        if not self.carrier_lanes:
            self.carrier_lanes = ("f32",)
        self.param_lanes = tuple(
            lane for lane in _LANE_DTYPES
            if any(lay.lengths.get(lane) for lay in self._param_layouts))
        if not self.param_lanes:
            self.param_lanes = ("f32",)
        self.carrier_len = {
            lane: max(lay.lengths.get(lane, 0)
                      for lay in self._carrier_layouts)
            for lane in self.carrier_lanes}
        self.param_len = {
            lane: max(lay.lengths.get(lane, 0)
                      for lay in self._param_layouts)
            for lane in self.param_lanes}

        # packed parameter buffers {lane: [P, L_lane]}
        rows = {lane: [] for lane in self.param_lanes}
        for lay, vals in zip(self._param_layouts, param_values):
            vecs = lay.pack(vals, self.param_lanes)
            padded = _pad_lanes(vecs, self.param_len)
            for lane in self.param_lanes:
                rows[lane].append(np.asarray(padded[lane]))
        self.packed_params = {
            lane: jnp.asarray(np.stack(rows[lane]))
            for lane in self.param_lanes}
        return self

    def pack_microbatch(self, feed):
        """feed dict -> {lane: [L_lane]} carrier for boundary 0.

        Values pass to ``pack`` RAW (numpy) — converting to jnp first
        would silently wrap int64 to int32 under x64-off before the
        range guard could fire."""
        lay = self._carrier_layouts[0]
        vecs = lay.pack({k: np.asarray(v) if not hasattr(v, "aval")
                         else v for k, v in feed.items()},
                        self.carrier_lanes)
        return _pad_lanes(vecs, self.carrier_len)

    def stack_microbatches(self, feeds):
        """[feed dicts] -> {lane: [M, L_lane]} ingest tensors."""
        packed = [self.pack_microbatch(f) for f in feeds]
        return {lane: jnp.stack([p[lane] for p in packed])
                for lane in self.carrier_lanes}

    def unpack_outputs(self, vecs):
        """One final-boundary carrier {lane: [L_lane]} -> fetch dict."""
        lay = self._carrier_layouts[-1]
        return lay.unpack({lane: vecs[lane][:lay.lengths.get(lane, 0)]
                           for lane in self.carrier_lanes})

    def select_fetch(self, outs, name):
        """{lane: [M, L]} stacked outputs -> [M, ...] values of one
        fetch target (lane-aware replacement for manual offset math)."""
        lay = self._carrier_layouts[-1]
        i = lay.names.index(name)
        lane, off, size = lay.lanes[i], lay.offsets[i], lay.sizes[i]
        sl = outs[lane][:, off:off + size]
        return sl.reshape((sl.shape[0],) + lay.shapes[i]) \
            .astype(_np_dtype(lay.dtypes[i]))

    def unpack_grads(self, packed_grads):
        """{lane: [P, L]} grads -> {param_name: grad} (multi-stage
        placements summed; integer-lane cotangents — float0 under
        ``jax.grad(..., allow_int=True)`` — are skipped)."""
        out = {}
        for s, lay in enumerate(self._param_layouts):
            for n, shape, dtype, lane, off, size in zip(
                    lay.names, lay.shapes, lay.dtypes, lay.lanes,
                    lay.offsets, lay.sizes):
                if lane == "i32":
                    continue
                g = packed_grads.get(lane)
                if g is None:
                    continue
                ga = np.asarray(g[s])
                if ga.dtype == object or ga.size == 0:  # float0 / empty
                    continue
                v = ga[off:off + size].reshape(shape)
                out[n] = out.get(n, 0) + np.asarray(v, np.float64)
        return out

    # -- stage functions ------------------------------------------------
    def _stage_branch(self, s):
        """carrier {lane: [L]} -> carrier {lane: [L]} for stage ``s``,
        given its packed param vectors; traced IR ops via the op
        registry (sub-block ops recurse through executor.lower_block)."""
        in_lay = self._carrier_layouts[s]
        out_lay = self._carrier_layouts[s + 1]
        p_lay = self._param_layouts[s]
        ops = self.stage_ops[s]
        registry = self._registry
        block = self.block
        amp = self.amp
        carrier_lanes = self.carrier_lanes
        carrier_len = self.carrier_len

        def branch(pvecs, carrier):
            env = p_lay.unpack(
                {lane: pvecs.get(lane, jnp.zeros((0,),
                                                 _LANE_DTYPES[lane]))
                 [:p_lay.lengths.get(lane, 0)]
                 for lane in set(p_lay.lanes)})
            env.update(in_lay.unpack(
                {lane: carrier[lane][:in_lay.lengths.get(lane, 0)]
                 for lane in set(in_lay.lanes)}))
            from paddle_tpu.executor import lower_block
            aux = {"rng_counter": 0, "amp": amp, "interpret": False,
                   "lod": {}, "block": block, "lower_block": lower_block}
            for op in ops:
                opdef = registry.resolve_lowering(op.type)
                ctx = registry.LowerContext(op, env, block, rng_key=None,
                                            training=True, aux=aux)
                opdef.lower(ctx)
                env.update(ctx.outputs)
            out = out_lay.pack(env, carrier_lanes)
            return _pad_lanes(out, carrier_len)

        return branch

    # -- the pipelined schedule ----------------------------------------
    def run_fn(self, data_axis=None):
        """Returns ``fn(packed_params {lane: [P, Lp]}, xs {lane: [M, L]})
        -> {lane: [M, L]}`` (final-boundary carriers per microbatch),
        jit/grad-able (``allow_int=True`` if an integer param exists).

        ``data_axis``: optional mesh axis name for dp x pp composition —
        microbatches are sharded over ``(data_axis, pipe_axis)`` and each
        data row runs an independent pipeline over its own microbatch
        block (params replicated across rows); outputs come back stacked
        in global microbatch order."""
        P = self.n_stages
        axis = self.axis
        mesh = self.mesh
        branches = [self._stage_branch(s) for s in range(P)]
        lanes = self.carrier_lanes
        L = self.carrier_len

        def per_device(params_local, xs_local):
            my_stage = jax.lax.axis_index(axis)
            pvecs = {lane: params_local[lane][0]
                     for lane in params_local}
            B = next(iter(xs_local.values())).shape[0]  # M / P block
            M = B * P
            n_ticks = M + P - 1
            outer = math.ceil(n_ticks / B)
            perm_fwd = [(i, (i + 1) % P) for i in range(P)]
            perm_ingest = [((i + 1) % P, i) for i in range(P)]

            def run_stage(carrier):
                return jax.lax.switch(
                    my_stage, [lambda c, b=b: b(pvecs, c)
                               for b in branches], carrier)

            def tick(t, state):
                buf, received, outputs = state
                mb_idx = t - my_stage
                active = (mb_idx >= 0) & (mb_idx < M)
                fresh = {
                    lane: jax.lax.dynamic_index_in_dim(
                        buf[lane], jnp.mod(t, B), axis=0, keepdims=False)
                    for lane in lanes}
                inp = {lane: jnp.where(my_stage == 0, fresh[lane],
                                       received[lane])
                       for lane in lanes}
                # double-where: bubble ticks must not FEED garbage into
                # the stage — a zero carrier can produce inf/nan (e.g. a
                # loss normalizer dividing by a zero token count) whose
                # cotangent poisons the masked output's gradient
                inp = {lane: jnp.where(active, v, jnp.ones_like(v))
                       for lane, v in inp.items()}
                out = run_stage(inp)
                out = {lane: jnp.where(active, v, jnp.zeros_like(v))
                       for lane, v in out.items()}
                outputs = jax.lax.cond(
                    active & (my_stage == P - 1),
                    lambda o: {
                        lane: jax.lax.dynamic_update_index_in_dim(
                            o[lane], out[lane],
                            jnp.clip(mb_idx, 0, M - 1), axis=0)
                        for lane in lanes},
                    lambda o: o, outputs)
                received = {
                    lane: jax.lax.ppermute(out[lane], axis, perm_fwd)
                    for lane in lanes}
                return buf, received, outputs

            received = {lane: jnp.zeros((L[lane],), _LANE_DTYPES[lane])
                        for lane in lanes}
            outputs = {lane: jnp.zeros((M, L[lane]), _LANE_DTYPES[lane])
                       for lane in lanes}
            buf = xs_local
            t0 = 0
            for _ in range(outer):
                def inner(i, state, t0=t0):
                    return tick(t0 + i, state)
                buf, received, outputs = jax.lax.fori_loop(
                    0, B, inner, (buf, received, outputs))
                # rotate ingest blocks one hop toward stage 0: after k
                # rotations device 0 holds block k, exactly when ticks
                # [kB, (k+1)B) consume it
                buf = {lane: jax.lax.ppermute(buf[lane], axis,
                                              perm_ingest)
                       for lane in lanes}
                t0 += B
            return {lane: jax.lax.psum(outputs[lane], axis)
                    for lane in lanes}

        from jax.sharding import PartitionSpec as PS
        mb_axes = (data_axis, axis) if data_axis else axis
        param_specs = {lane: PS(axis) for lane in self.param_lanes}
        xs_specs = {lane: PS(mb_axes) for lane in lanes}
        out_specs = {lane: PS(data_axis) if data_axis else PS()
                     for lane in lanes}
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(param_specs, xs_specs),
                       out_specs=out_specs,
                       **{_SM_CHECK_KW: False})
        return fn


def pipeline_transpiler(program, n_stages, feed_names, fetch_names,
                        mesh, axis="pipe"):
    """Split ``program`` into ``n_stages`` balanced pipeline stages.

    Returns a :class:`PipelinedProgram`; call ``.build(scope,
    sample_microbatch)`` after running the startup program, then
    ``.run_fn()`` for the differentiable pipelined step."""
    return PipelinedProgram(program, n_stages, feed_names, fetch_names,
                            mesh, axis)
