"""IR-level pipeline partitioning: split a Program's op list into P
balanced stages and run it as a GPipe pipeline (VERDICT r3 item 3 —
completes ``parallel/pipeline.py``'s primitive into a framework feature).

The reference has no pipeline parallelism; SURVEY.md §2.8 names PP as a
beyond-reference row.  Design:

* ``split_program``: walk the global block's ops in program order,
  weight them with the same analytic FLOP model the benchmarks use
  (conv/matmul dominate), and cut at the P-quantiles of cumulative
  cost.  Any cut is legal: everything produced before the cut and
  consumed after it becomes part of the boundary *carrier*.
* Stages are NON-homogeneous (different ops, params, shapes).  Each
  stage's parameters are flat-packed into one f32 vector; the P vectors
  are padded to a common length and stacked [P, Lp] — sharded over the
  ``pipe`` mesh axis, so each device stores only its own stage's
  weights.  Inside ``shard_map`` a ``lax.switch`` on the device's stage
  index unpacks its slice and runs its stage's traced IR ops.
* Activations/feeds cross boundaries the same way: a flat f32 carrier
  of uniform (max-boundary) length.  Integer feeds ride the carrier as
  exact f32 (vocab ids < 2^24).
* Microbatches feed STAGE 0 ONLY (the refinement pipeline.py:70-73
  names): the [M, L0] ingest tensor is sharded over ``pipe`` in
  contiguous blocks of B = M/P; after every B ticks the local blocks
  rotate one hop toward stage 0 on the ICI ring, arriving exactly when
  stage 0 needs them — devices never hold the full microbatch set.
* The whole schedule is differentiable: ``jax.grad`` w.r.t. the packed
  [P, Lp] buffer yields the reverse pipeline, and ``unpack_grads``
  scatters it back to named parameters (parameters used by several
  stages get their contributions summed).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import Parameter

try:
    from jax import shard_map
    _SM_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
    _SM_CHECK_KW = "check_rep"

__all__ = ["pipeline_transpiler", "PipelinedProgram"]

_SKIP = ("feed", "fetch")


def _op_cost(op, block):
    """Analytic op weight (same accounting as bench_resnet/bench.py)."""
    try:
        if op.type in ("conv2d", "depthwise_conv2d"):
            filt = block.var(op.input("Filter")[0])
            out = block.var(op.output("Output")[0])
            co, ci, kh, kw = filt.shape
            n, _, ho, wo = out.shape
            return 2 * n * ho * wo * co * ci * kh * kw
        if op.type in ("mul", "matmul"):
            x = block.var(op.input("X")[0])
            y = block.var(op.input("Y")[0])
            k, n = y.shape[-2], y.shape[-1]
            m = int(np.prod([d for d in x.shape if d and d > 0])) // max(
                int(k), 1)
            return 2 * m * int(k) * int(n)
        if op.type == "scaled_dot_product_attention":
            q = block.var(op.input("Q")[0])
            b, h, s, d = q.shape
            return 4 * b * h * s * s * d
    except Exception:
        pass
    return 1


def _all_input_names(op):
    return [n for vs in op.inputs.values() for n in vs]


def _all_output_names(op):
    return [n for vs in op.outputs.values() for n in vs]


def split_program(program, n_stages, feed_names, fetch_names):
    """Balanced cut points + per-stage op/param/boundary metadata."""
    block = program.global_block()
    ops = [op for op in block.ops if op.type not in _SKIP]
    for op in ops:
        for a in op.attrs.values():
            if a.__class__.__name__ == "Block":
                raise ValueError(
                    f"pipeline_transpiler: op {op.type!r} carries a "
                    f"sub-block; control flow inside a pipelined program "
                    f"is not supported — pipeline the flat region only")

    costs = [_op_cost(op, block) for op in ops]
    total = float(sum(costs))
    # cut after reaching each quantile of cumulative cost
    cuts, acc, next_q = [], 0.0, 1
    for i, c in enumerate(costs):
        acc += c
        if next_q < n_stages and acc >= total * next_q / n_stages:
            cuts.append(i + 1)
            next_q += 1
    while len(cuts) < n_stages - 1:   # degenerate tails
        cuts.append(len(ops))
    stage_ops = []
    lo = 0
    for cut in cuts + [len(ops)]:
        stage_ops.append(ops[lo:cut])
        lo = cut

    def is_param(name):
        v = block.var(name) if name in block.vars else None
        return v is not None and (isinstance(v, Parameter)
                                  or getattr(v, "persistable", False))

    produced_by = {}
    for s, sops in enumerate(stage_ops):
        for op in sops:
            for n in _all_output_names(op):
                produced_by.setdefault(n, s)

    stage_params = []
    for sops in stage_ops:
        names = []
        for op in sops:
            for n in _all_input_names(op):
                if is_param(n) and n not in names:
                    names.append(n)
        stage_params.append(names)

    # boundary b carries everything still needed past it and produced
    # before it: inputs of stage >= b ops, plus fetch targets already
    # produced (they must ride through to the final boundary); feeds
    # count as produced before stage 0
    feed_set = set(feed_names)
    boundaries = []
    for b in range(n_stages + 1):
        need = set()
        for n in fetch_names:
            src = produced_by.get(n)
            if b == n_stages or (src is not None and src < b):
                need.add(n)
        for s in range(b, n_stages):
            for op in stage_ops[s]:
                for n in _all_input_names(op):
                    if is_param(n):
                        continue
                    src = produced_by.get(n)
                    if (src is None and n in feed_set) or \
                            (src is not None and src < b):
                        need.add(n)
        boundaries.append(sorted(need))
    return block, stage_ops, stage_params, boundaries


class _Layout:
    """Flat-packing layout for a list of named tensors."""

    def __init__(self, names, shapes, dtypes):
        self.names = list(names)
        self.shapes = [tuple(s) for s in shapes]
        self.dtypes = list(dtypes)
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = np.cumsum([0] + self.sizes).tolist()
        self.length = self.offsets[-1]

    def pack(self, values):
        flats = [jnp.ravel(values[n]).astype(jnp.float32)
                 for n in self.names]
        if not flats:
            return jnp.zeros((0,), jnp.float32)
        return jnp.concatenate(flats)

    def unpack(self, vec):
        out = {}
        for n, shape, dtype, off, size in zip(
                self.names, self.shapes, self.dtypes, self.offsets,
                self.sizes):
            out[n] = jax.lax.slice(vec, (off,), (off + size,)) \
                .reshape(shape).astype(dtype)
        return out


class PipelinedProgram:
    """A Program split into P pipeline stages; call :meth:`run` (or
    differentiate :meth:`loss_fn`) with per-microbatch feeds."""

    def __init__(self, program, n_stages, feed_names, fetch_names, mesh,
                 axis="pipe"):
        from paddle_tpu.ops import registry as _registry
        self._registry = _registry
        self.mesh = mesh
        self.axis = axis
        self.n_stages = n_stages
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        (self.block, self.stage_ops, self.stage_param_names,
         self.boundaries) = split_program(program, n_stages, feed_names,
                                          fetch_names)
        for sops in self.stage_ops:
            for op in sops:
                opdef = _registry.lookup(op.type)
                if opdef is not None and opdef.uses_rng:
                    raise ValueError(
                        f"pipeline_transpiler: op {op.type!r} uses the "
                        f"rng stream; run with dropout/sampling disabled "
                        f"in the pipelined region")

    # -- layouts (need var shapes; resolved against scope values) -------
    def _var_meta(self, name, scope_vals):
        v = self.block.var(name) if name in self.block.vars else None
        if name in scope_vals:
            arr = np.asarray(scope_vals[name])
            return arr.shape, arr.dtype
        if v is None or v.shape is None:
            raise ValueError(f"pipeline_transpiler: no shape for {name!r}")
        shape = tuple(int(d) for d in v.shape)
        return shape, np.dtype(v.dtype if v.dtype != "bfloat16"
                               else np.float32)

    def build(self, scope, microbatch_feeds):
        """Finalize layouts from the startup-initialized ``scope`` and a
        SAMPLE microbatch feed dict (fixes the microbatch shapes)."""
        sample = {k: np.asarray(v) for k, v in microbatch_feeds.items()}
        self._param_layouts = []
        param_values = []     # local: only needed to build packed_params
        for names in self.stage_param_names:
            vals = {n: np.asarray(scope.find_var(n)) for n in names}
            lay = _Layout(names, [vals[n].shape for n in names],
                          [vals[n].dtype for n in names])
            self._param_layouts.append(lay)
            param_values.append(vals)

        self._carrier_layouts = []
        for b, names in enumerate(self.boundaries):
            shapes, dtypes = [], []
            for n in names:
                if n in sample:
                    shapes.append(sample[n].shape)
                    dtypes.append(sample[n].dtype)
                else:
                    s, d = self._var_meta(n, {})
                    shapes.append(s)
                    dtypes.append(d)
            self._carrier_layouts.append(_Layout(names, shapes, dtypes))
        self.carrier_len = max(l.length for l in self._carrier_layouts)
        self.param_len = max((l.length for l in self._param_layouts),
                             default=0)
        # packed parameter buffer [P, Lp]
        rows = []
        for lay, vals in zip(self._param_layouts, param_values):
            vec = np.zeros(self.param_len, np.float32)
            flat = np.concatenate(
                [np.asarray(vals[n], np.float32).ravel()
                 for n in lay.names]) if lay.names else \
                np.zeros(0, np.float32)
            vec[:flat.size] = flat
            rows.append(vec)
        self.packed_params = jnp.asarray(np.stack(rows))
        return self

    def pack_microbatch(self, feed):
        lay = self._carrier_layouts[0]
        vec = lay.pack({k: jnp.asarray(v) for k, v in feed.items()})
        pad = self.carrier_len - lay.length
        return jnp.pad(vec, (0, pad)) if pad else vec

    def unpack_outputs(self, vec):
        lay = self._carrier_layouts[-1]
        return lay.unpack(vec[:lay.length])

    def unpack_grads(self, packed_grads):
        """[P, Lp] grads -> {param_name: grad} (multi-stage placements
        summed)."""
        out = {}
        g = np.asarray(packed_grads)
        for s, lay in enumerate(self._param_layouts):
            vals = lay.unpack(jnp.asarray(g[s][:lay.length]))
            for n, v in vals.items():
                out[n] = out.get(n, 0) + np.asarray(v, np.float64)
        return out

    # -- stage functions ------------------------------------------------
    def _stage_branch(self, s):
        """carrier [L] -> carrier [L] for stage ``s``, given its packed
        param vector; traced IR ops via the op registry."""
        in_lay = self._carrier_layouts[s]
        out_lay = self._carrier_layouts[s + 1]
        p_lay = self._param_layouts[s]
        ops = self.stage_ops[s]
        registry = self._registry
        block = self.block

        def branch(pvec, carrier):
            env = p_lay.unpack(pvec[:p_lay.length] if p_lay.length
                               else pvec[:0])
            env.update(in_lay.unpack(carrier[:in_lay.length]))
            aux = {"rng_counter": 0, "amp": False, "interpret": False,
                   "lod": {}, "block": block}
            for op in ops:
                opdef = registry.resolve_lowering(op.type)
                ctx = registry.LowerContext(op, env, block, rng_key=None,
                                            training=True, aux=aux)
                opdef.lower(ctx)
                env.update(ctx.outputs)
            out = out_lay.pack(env)
            pad = self.carrier_len - out_lay.length
            return jnp.pad(out, (0, pad)) if pad else out

        return branch

    # -- the pipelined schedule ----------------------------------------
    def run_fn(self):
        """Returns ``fn(packed_params [P, Lp], xs [M, L]) -> [M, L]``
        (final-boundary carriers per microbatch), jit/grad-able."""
        P = self.n_stages
        axis = self.axis
        mesh = self.mesh
        branches = [self._stage_branch(s) for s in range(P)]
        L = self.carrier_len

        def per_device(params_local, xs_local):
            my_stage = jax.lax.axis_index(axis)
            pvec = params_local[0]
            B = xs_local.shape[0]          # M / P ingest block
            M = B * P
            n_ticks = M + P - 1
            outer = math.ceil(n_ticks / B)
            perm_fwd = [(i, (i + 1) % P) for i in range(P)]
            perm_ingest = [((i + 1) % P, i) for i in range(P)]

            def run_stage(carrier):
                return jax.lax.switch(
                    my_stage, [lambda c, b=b: b(pvec, c)
                               for b in branches], carrier)

            def tick(t, state):
                buf, received, outputs = state
                mb_idx = t - my_stage
                active = (mb_idx >= 0) & (mb_idx < M)
                fresh = jax.lax.dynamic_index_in_dim(
                    buf, jnp.mod(t, B), axis=0, keepdims=False)
                inp = jnp.where(my_stage == 0, fresh, received)
                # double-where: bubble ticks must not FEED garbage into
                # the stage — a zero carrier can produce inf/nan (e.g. a
                # loss normalizer dividing by a zero token count) whose
                # cotangent poisons the masked output's gradient
                inp = jnp.where(active, inp, jnp.ones_like(inp))
                out = run_stage(inp)
                out = jnp.where(active, out, jnp.zeros_like(out))
                outputs = jax.lax.cond(
                    active & (my_stage == P - 1),
                    lambda o: jax.lax.dynamic_update_index_in_dim(
                        o, out, jnp.clip(mb_idx, 0, M - 1), axis=0),
                    lambda o: o, outputs)
                received = jax.lax.ppermute(out, axis, perm_fwd)
                return buf, received, outputs

            received = jnp.zeros((L,), jnp.float32)
            outputs = jnp.zeros((M, L), jnp.float32)
            buf = xs_local
            t0 = 0
            for _ in range(outer):
                def inner(i, state, t0=t0):
                    return tick(t0 + i, state)
                buf, received, outputs = jax.lax.fori_loop(
                    0, B, inner, (buf, received, outputs))
                # rotate ingest blocks one hop toward stage 0: after k
                # rotations device 0 holds block k, exactly when ticks
                # [kB, (k+1)B) consume it
                buf = jax.lax.ppermute(buf, axis, perm_ingest)
                t0 += B
            return jax.lax.psum(outputs, axis)

        from jax.sharding import PartitionSpec as PS
        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(PS(axis), PS(axis)), out_specs=PS(),
                       **{_SM_CHECK_KW: False})
        return fn


def pipeline_transpiler(program, n_stages, feed_names, fetch_names,
                        mesh, axis="pipe"):
    """Split ``program`` into ``n_stages`` balanced pipeline stages.

    Returns a :class:`PipelinedProgram`; call ``.build(scope,
    sample_microbatch)`` after running the startup program, then
    ``.run_fn()`` for the differentiable pipelined step."""
    return PipelinedProgram(program, n_stages, feed_names, fetch_names,
                            mesh, axis)
