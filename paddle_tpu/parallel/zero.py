"""ZeRO-style optimizer-state sharding over the ``data`` mesh axis.

Parameters stay replicated (every dp rank holds the full model), but the
optimizer *state* — momentum/moment accumulators, which for Adam is 2x
the parameter memory — is partitioned so each dp rank owns a ``1/N``
slice (Rajbhandari et al., ZeRO stage 1; the reference's closest analog
is the pserver owning the optimizer state of its parameter shard).

Realization: a :class:`ZeroPlan` assigns every eligible accumulator a
``PartitionSpec('data', ...)`` placement on its leading dim.  Fed to
``ParallelExecutor(zero=...)`` the placements become jit
``in_shardings``/``out_shardings``, and GSPMD lowers the update to the
classic ZeRO schedule — gradients reduce-scattered into the owned state
slice, updated params all-gathered back to replicas — without a manual
collective schedule.  The explicit :func:`reduce_scatter_grads` /
:func:`allgather_params` helpers (built on ``parallel/collective.py``)
are the shard_map form of the same step for code that manages the axis
itself.

Before any chip runs, the plan is *proved*: the placements are emitted
as IR-level sharding facts through
``analysis.distributed.check_sharding`` (PTA016/PTA017), so an
inconsistent plan — e.g. ``moment1`` sharded but ``moment2`` replicated
for the same parameter — fails statically, not as a silent reshard or
an OOM three hours in.
"""

from __future__ import annotations

import re

__all__ = ["ZeroPlan", "zero_plan", "OPTIMIZER_STATE_SLOTS",
           "SCALAR_STATE_SLOTS", "reduce_scatter_grads",
           "allgather_params"]

#: optimizer op type -> the input slots holding param-shaped state
#: tensors (the shardable accumulators).  Scalar bookkeeping slots
#: (beta-power accumulators, shape [1]) are deliberately absent: they
#: stay replicated by construction.
OPTIMIZER_STATE_SLOTS = {
    "sgd": (),
    "momentum": ("Velocity",),
    "adagrad": ("Moment",),
    "adam": ("Moment1", "Moment2"),
    "adamax": ("Moment", "InfNorm"),
    "decayed_adagrad": ("Moment",),
    "adadelta": ("AvgSquaredGrad", "AvgSquaredUpdate"),
    "rmsprop": ("Moment", "MeanSquare"),
    "ftrl": ("SquaredAccumulator", "LinearAccumulator"),
}

#: slots that are scalars by contract and must never be sharded
SCALAR_STATE_SLOTS = ("Beta1Pow", "Beta2Pow")


# -- shard_map-form collectives (built on parallel/collective.py) -----------

def reduce_scatter_grads(grad, axis_name):
    """The ZeRO gradient step inside an explicit ``shard_map``: reduce
    the replicas' gradients AND hand each rank only its owned 1/N slice
    (dim 0), in one fused collective."""
    from paddle_tpu.parallel import collective
    return collective.reduce_scatter(grad, axis_name, scatter_dimension=0)


def allgather_params(update_slice, axis_name):
    """The ZeRO parameter step inside an explicit ``shard_map``:
    re-materialize the full (replicated) tensor from each rank's owned
    slice along dim 0."""
    from paddle_tpu.parallel import collective
    return collective.all_gather(update_slice, axis_name, axis=0,
                                 tiled=True)


class ZeroPlan:
    """The sharding facts of one program's ZeRO partitioning.

    ``placements`` maps accumulator names to placement tuples
    (``('data', None, ...)``); ``replicated`` maps the params/grads the
    plan saw to ``()`` (known-replicated — the facts the verifier needs
    to prove Param/Grad/state agreement).  ``skipped`` lists
    accumulators the plan left replicated, with the reason (scalar
    slot, indivisible leading dim), so an operator can see what did NOT
    shard without diffing memory profiles.
    """

    def __init__(self, program, axis, num_shards):
        self.program = program
        self.axis = axis
        self.num_shards = int(num_shards)
        self.placements = {}     # accumulator -> ('data', None, ...)
        self.replicated = {}     # param/grad -> ()
        self.skipped = {}        # accumulator -> reason string

    def __bool__(self):
        return bool(self.placements)

    def all_placements(self):
        """Every fact the plan asserts, accumulators and params/grads
        together — the input to the PTA016/PTA017 sharding pass."""
        merged = dict(self.replicated)
        merged.update(self.placements)
        return merged

    def rules(self):
        """``(regex, PartitionSpec)`` rules for
        ``ParallelExecutor(param_shardings=...)`` — one exact-name rule
        per sharded accumulator."""
        from jax.sharding import PartitionSpec as P
        out = []
        for name, spec in sorted(self.placements.items()):
            out.append((f"^{re.escape(name)}$", P(*spec)))
        return out

    def checkpoint_specs(self):
        """name -> placement for the per-shard checkpoint writer (the
        sharded accumulators; replicated vars default to one shard)."""
        return dict(self.placements)

    def verify(self, mesh_axes=None, raise_on_error=True):
        """Prove the plan against the program IR through the
        distributed sharding pass (PTA016 errors / PTA017 warnings)
        BEFORE any device sees it.  Returns the diagnostics; raises
        :class:`~paddle_tpu.analysis.diagnostics.ProgramVerificationError`
        on errors unless ``raise_on_error=False``."""
        from paddle_tpu.analysis.diagnostics import \
            ProgramVerificationError
        from paddle_tpu.analysis.distributed import check_sharding
        if mesh_axes is None:
            mesh_axes = {self.axis: self.num_shards}
        diags = check_sharding(self.program, self.all_placements(),
                               mesh_axes=mesh_axes,
                               program_label="zero-plan")
        errors = [d for d in diags if d.severity == "error"]
        if errors and raise_on_error:
            raise ProgramVerificationError(errors, where="zero_plan")
        return diags


def zero_plan(program, mesh, axis="data", skip=None):
    """Build (and statically verify) the ZeRO partitioning of
    ``program``'s optimizer state over mesh axis ``axis``.

    ``skip``: optional predicate over accumulator names; matching vars
    stay replicated (the ParallelExecutor wiring uses this to keep
    user TP-ruled state out of the plan — first rule wins).  A 1-sized
    (or absent) axis yields an empty, falsy plan: single-device runs
    and pure-TP meshes pay nothing.
    """
    sizes = dict(zip(mesh.axis_names,
                     getattr(mesh.devices, "shape", ())))
    dp = int(sizes.get(axis, 1))
    plan = ZeroPlan(program, axis, dp)
    if dp <= 1:
        return plan
    block = program.global_block()
    for op in block.ops:
        slots = OPTIMIZER_STATE_SLOTS.get(op.type)
        if slots is None:
            continue
        for pg_slot in ("Param", "Grad"):
            for name in op.input(pg_slot):
                plan.replicated.setdefault(name, ())
        for slot in slots:
            for name in op.input(slot):
                if skip is not None and skip(name):
                    plan.skipped[name] = "matched a user sharding rule"
                    continue
                try:
                    var = block.var(name)
                except KeyError:
                    plan.skipped[name] = "not a program variable"
                    continue
                shape = var.shape
                if not shape or shape[0] is None or int(shape[0]) <= 0:
                    plan.skipped[name] = "unknown leading dim"
                    continue
                if int(shape[0]) % dp != 0:
                    plan.skipped[name] = (
                        f"dim 0 of {int(shape[0])} not divisible by "
                        f"{axis}={dp}")
                    continue
                plan.placements[name] = \
                    (axis,) + (None,) * (len(shape) - 1)
    return plan
