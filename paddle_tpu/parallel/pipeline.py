"""Pipeline parallelism: GPipe-style microbatched stage pipeline over a
mesh axis.

The reference has NO pipeline parallelism (its §2.8 inventory is
dp/pserver); this is a TPU-native forward-looking primitive completing the
parallelism set (dp = batch sharding, tp = weight PartitionSpecs, sp =
ring_attention, ep = vocab-sharded tables, pp = this module).

Design (the "pipelined scan" from the public scaling-book recipe):

* P homogeneous stages live on the ``pipe`` mesh axis; stage parameters
  are STACKED on a leading [P] axis sharded over that axis, so each
  device holds exactly its stage's weights.
* One ``lax.fori_loop`` runs M + P - 1 ticks.  At tick t, stage p works
  on microbatch t - p (a masked bubble otherwise); activations hop
  p -> p+1 on the ICI ring with ``ppermute``.
* The whole schedule is a pure differentiable function: ``jax.grad``
  through it yields the reverse pipeline automatically (ppermute's
  transpose is the reverse ppermute) — no hand-written backward schedule.

``gpipe`` is the generic primitive (stage_fn + stacked params); see
``tests/test_pipeline.py`` for the loss/grad equality proof against the
sequential computation on an 8-device CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
    _SM_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
    _SM_CHECK_KW = "check_rep"

__all__ = ["gpipe", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[{name: array}, ...] per stage -> {name: [P, ...] stacked} (shard
    the leading axis over the ``pipe`` mesh axis before calling gpipe)."""
    keys = per_stage_params[0].keys()
    for p in per_stage_params[1:]:
        if p.keys() != keys:
            raise ValueError("pipeline stages must be homogeneous "
                             "(same parameter names/shapes)")
        for k in keys:
            if p[k].shape != per_stage_params[0][k].shape:
                raise ValueError(
                    f"pipeline stages must be homogeneous: param {k!r} "
                    f"has shape {p[k].shape} vs "
                    f"{per_stage_params[0][k].shape}")
    return {k: jnp.stack([p[k] for p in per_stage_params])
            for k in keys}


def gpipe(stage_fn, stacked_params, microbatches, mesh: Mesh,
          axis: str = "pipe"):
    """Run ``microbatches`` [M, mb, ...] through P pipelined stages.

    ``stage_fn(params, x) -> y`` is one stage's computation (same shape
    in and out); ``stacked_params`` is a pytree whose leaves have a
    leading [P] stage axis.  Returns [M, mb, ...] outputs (the last
    stage's results, gathered).  Fully differentiable — take ``jax.grad``
    of a loss over the returned outputs w.r.t. ``stacked_params``.

    Memory note: microbatch inputs are replicated across stages (every
    device holds [M, mb, ...]); in the deepest-memory regimes the next
    refinement is feeding stage 0 only (shard the M axis + an ingest
    ppermute) at the cost of schedule complexity.
    """
    if axis not in mesh.shape:
        raise ValueError(f"gpipe: mesh has no axis {axis!r} "
                         f"(axes: {list(mesh.shape)})")
    p_size = mesh.shape[axis]
    m = microbatches.shape[0]
    leading = {leaf.shape[0] for leaf in
               jax.tree_util.tree_leaves(stacked_params)}
    if leading != {p_size}:
        raise ValueError(
            f"gpipe: stacked stage params have leading dim(s) "
            f"{sorted(leading)} but the {axis!r} mesh axis has {p_size} "
            f"devices — one stage per device (got a divisible-but-wrong "
            f"stage count? shard_map would silently drop stages)")

    def per_device(params, xs):
        # params: leaves [1, ...] (this stage); xs [M, mb, ...] replicated
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        my_stage = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        perm_fwd = [(i, (i + 1) % p_size) for i in range(p_size)]

        def tick(t, carry):
            received, outputs = carry
            mb_idx = t - my_stage
            active = (mb_idx >= 0) & (mb_idx < m)
            # stage 0 ingests a fresh microbatch; others take the ring
            fresh = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            inp = jnp.where(my_stage == 0, fresh, received)
            out = stage_fn(params, inp)
            out = jnp.where(active, out, jnp.zeros_like(out))
            # last stage banks its finished microbatch
            outputs = jax.lax.cond(
                active & (my_stage == p_size - 1),
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, jnp.clip(mb_idx, 0, m - 1), axis=0),
                lambda o: o, outputs)
            received = jax.lax.ppermute(out, axis, perm_fwd)
            return received, outputs

        received0 = jnp.zeros(mb_shape, xs.dtype)
        outputs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        _, outputs = jax.lax.fori_loop(0, m + p_size - 1, tick,
                                       (received0, outputs0))
        # every device returns the SAME gathered outputs: only the last
        # stage holds real values, so a psum broadcasts them (zeros
        # elsewhere) — keeps the caller mesh-agnostic
        return jax.lax.psum(outputs, axis)

    spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(spec_params, P()), out_specs=P(),
                   **{_SM_CHECK_KW: False})
    return fn(stacked_params, microbatches)
