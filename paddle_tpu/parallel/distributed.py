"""Multi-host bootstrap + cluster env conventions.

Replaces all four of the reference's distributed backends (SURVEY.md §5.8:
NCCL, gRPC pserver, v2 epoll sockets, Go net/rpc+etcd) with the JAX
multi-controller model: every host runs the same program,
``jax.distributed.initialize`` forms the cluster over DCN, and GSPMD/ICI
carry the tensor traffic.  The reference's env conventions
(``PADDLE_INIT_PSERVERS``/``TRAINER_ID``/``TRAINERS``,
benchmark/cluster/vgg16/fluid_trainer.yaml) map onto
coordinator-address/process-id/num-processes.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size",
           "global_mesh"]

_initialized = False


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None, local_device_ids=None):
    """Form the multi-host cluster (reference analog: trainer startup in
    ``distribute_transpiler``-mode + NCCL init / pserver discovery).

    Resolution order for each field: explicit arg > PADDLE_* env (reference
    convention) > JAX defaults (TPU pod metadata)."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        pservers = os.environ.get("PADDLE_INIT_PSERVERS")
        coordinator_address = os.environ.get(
            "PADDLE_COORDINATOR", pservers.split(",")[0] + ":8357"
            if pservers else None)
    if num_processes is None:
        t = os.environ.get("PADDLE_INIT_NUM_GRADIENT_SERVERS") or \
            os.environ.get("PADDLE_TRAINERS") or os.environ.get("TRAINERS")
        num_processes = int(t) if t else None
    if process_id is None:
        t = os.environ.get("PADDLE_INIT_TRAINER_ID") or \
            os.environ.get("PADDLE_TRAINER_ID") or \
            os.environ.get("TRAINER_ID")
        process_id = int(t) if t else None

    if coordinator_address is None and num_processes is None:
        # single-host (or TPU pod auto-bootstrap)
        try:
            jax.distributed.initialize()
        except Exception:
            pass  # single-process; jax.devices() is already correct
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            local_device_ids=local_device_ids)
    _initialized = True


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def global_mesh(mesh_shape=None, axis_names=None):
    """Mesh over ALL devices across hosts (ICI within a slice, DCN
    between); shape defaults to 1-D data parallelism."""
    from paddle_tpu.parallel.mesh import make_mesh
    return make_mesh(mesh_shape, axis_names, devices=jax.devices())
