"""Device-mesh management (the TPU-native analog of the reference's
``platform/nccl_helper.h`` NCCLContextMap: device discovery + communicator
setup — here, a ``jax.sharding.Mesh`` whose collectives ride the ICI).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "set_default_mesh", "device_count"]

_default_mesh = None

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"


def device_count():
    return jax.device_count()


def make_mesh(mesh_shape=None, axis_names=None, devices=None):
    """Build a Mesh.  Default: all devices on one ``data`` axis."""
    devices = devices if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devices),)
        axis_names = axis_names or (DATA_AXIS,)
    axis_names = axis_names or tuple(
        f"axis{i}" for i in range(len(mesh_shape)))
    arr = np.asarray(devices[:int(np.prod(mesh_shape))]).reshape(mesh_shape)
    return Mesh(arr, axis_names)


def default_mesh():
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh):
    global _default_mesh
    _default_mesh = mesh
    return mesh
