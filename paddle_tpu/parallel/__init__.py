"""Mesh-sharded parallel execution.

Replaces the reference's multi-device stack (§2.8 of SURVEY.md):
  * ``ParallelExecutor`` + SSA graph + NCCLAllReduceOpHandle
    (``paddle/fluid/framework/parallel_executor.cc:53``,
    ``details/multi_devices_graph_builder.cc:79``) → one ``jit`` of the
    whole training step with the batch dimension sharded over a
    ``jax.sharding.Mesh`` and parameters replicated; XLA's SPMD partitioner
    inserts the gradient all-reduce over ICI automatically.
  * ``DistributeTranspiler`` pserver rewrite → sharding-spec partitioning
    (``paddle_tpu.parallel.distribute_transpiler``).
  * NCCL collective ops → collective IR ops lowering to
    ``lax.psum``/``all_gather``/... (``paddle_tpu.parallel.collective``).
  * Go master fault-tolerant data dispatch → ``paddle_tpu.parallel.master``.
  * Sequence/context parallelism (absent in the reference) →
    ``paddle_tpu.parallel.ring_attention``.
"""

from paddle_tpu.parallel.mesh import (default_mesh, make_mesh,
                                      device_count, set_default_mesh)
from paddle_tpu.parallel.parallel_executor import ParallelExecutor
from paddle_tpu.parallel.distribute_transpiler import (DistributeTranspiler,
                                                       DistributedSpec)
from paddle_tpu.parallel import collective  # registers c_* IR ops
from paddle_tpu.parallel.ring_attention import ring_attention
from paddle_tpu.parallel.master import MasterService, partition_files
from paddle_tpu.parallel.distributed import (init_parallel_env, get_rank,
                                             get_world_size, global_mesh)
from paddle_tpu.parallel.zero import ZeroPlan, zero_plan

__all__ = ["ParallelExecutor", "default_mesh", "make_mesh", "device_count",
           "set_default_mesh", "DistributeTranspiler", "DistributedSpec",
           "collective", "ring_attention", "MasterService",
           "partition_files", "init_parallel_env", "get_rank",
           "get_world_size", "global_mesh", "ZeroPlan", "zero_plan"]
