"""Mesh-sharded parallel execution.

Replaces the reference's multi-device stack (§2.8 of SURVEY.md):
  * ``ParallelExecutor`` + SSA graph + NCCLAllReduceOpHandle
    (``paddle/fluid/framework/parallel_executor.cc:53``,
    ``details/multi_devices_graph_builder.cc:79``) → one ``jit`` of the
    whole training step with the batch dimension sharded over a
    ``jax.sharding.Mesh`` and parameters replicated; XLA's SPMD partitioner
    inserts the gradient all-reduce over ICI automatically.
  * ``DistributeTranspiler`` pserver rewrite → sharding-spec partitioning
    (``paddle_tpu.parallel.distribute_transpiler``).
  * NCCL collective ops → collective IR ops lowering to
    ``lax.psum``/``all_gather``/... (``paddle_tpu.ops.collective_ops``).
"""

from paddle_tpu.parallel.mesh import (default_mesh, make_mesh,
                                      device_count, set_default_mesh)
from paddle_tpu.parallel.parallel_executor import ParallelExecutor
from paddle_tpu.parallel.distribute_transpiler import (DistributeTranspiler,
                                                       DistributedSpec)

__all__ = ["ParallelExecutor", "default_mesh", "make_mesh", "device_count",
           "set_default_mesh", "DistributeTranspiler", "DistributedSpec"]
