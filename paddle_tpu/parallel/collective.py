"""Collective communication as IR ops + helpers.

Parity with the reference's NCCL op family (``operators/nccl_op.cc``:
NCCLAllReduce :94, NCCLReduce :140, NCCLBcast :191) and the collective
needs of the pserver path — all superseded by XLA collectives that GSPMD
rides over ICI.  Two layers:

  * **IR ops** ``c_allreduce_{sum,max,min,prod}``, ``c_broadcast``,
    ``c_allgather``, ``c_reducescatter``, ``c_alltoall`` — usable inside
    programs.  Outside an spmd axis context they are identity/no-op (one
    logical device: the whole mesh, GSPMD partitions underneath), matching
    how the TPU build subsumes explicit per-device communication.  Inside
    a ``shard_map`` lowering (``ctx.aux['spmd_axis']``) they emit real
    ``lax.psum``/``all_gather``/... on that axis.
  * **Python helpers** for direct use in shard_map'd code
    (ring attention uses ``lax.ppermute`` directly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.obs.trace import span as _span
from paddle_tpu.ops.registry import (
    register_op, LowerContext, infer_shape_unary, ShapeInferenceSkip)

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "all_to_all",
           "broadcast"]


# -- python helpers (require an active named axis) --------------------------
#
# The spans here measure STAGING time (these run at trace time inside a
# jit/shard_map lowering — device-side collective time lives in the
# XProf trace); what they buy the span timeline is WHICH collectives a
# step emits, with axis names, in program order.

def all_reduce(x, axis_name, op="sum"):
    with _span("collective.all_reduce", axis=str(axis_name), op=op):
        return {"sum": jax.lax.psum, "max": jax.lax.pmax,
                "min": jax.lax.pmin}[op](x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    with _span("collective.all_gather", axis=str(axis_name)):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    with _span("collective.reduce_scatter", axis=str(axis_name)):
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=scatter_dimension,
                                    tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis):
    with _span("collective.all_to_all", axis=str(axis_name)):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)


def broadcast(x, axis_name, root=0):
    with _span("collective.broadcast", axis=str(axis_name)):
        # select root's value on every member of the axis
        idx = jax.lax.axis_index(axis_name)
        src = jax.lax.all_gather(x, axis_name, axis=0)
        del idx
        return src[root]


# -- IR ops -----------------------------------------------------------------

def _axis(ctx: LowerContext):
    # an explicit ``axis`` attr pins the collective to a named mesh
    # axis AT THE IR LEVEL — a program-order fact the distributed
    # verifier (analysis/distributed.py PTA011/PTA012) can then prove
    # consistent across replicas/stages; without it the axis is the
    # lowering context's spmd axis, as before
    return ctx.attr("axis", None) or ctx.aux.get("spmd_axis")


def _make_allreduce(op_name, reducer):
    @register_op(op_name, infer_shape=infer_shape_unary())
    def lower(ctx: LowerContext):
        x = ctx.input("X")
        ax = _axis(ctx)
        ctx.set_output("Out", x if ax is None else reducer(x, ax))
    return lower


_make_allreduce("c_allreduce_sum", jax.lax.psum)
_make_allreduce("c_allreduce_max", jax.lax.pmax)
_make_allreduce("c_allreduce_min", jax.lax.pmin)
_make_allreduce("c_allreduce_prod",
                lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)))


@register_op("c_broadcast", infer_shape=infer_shape_unary())
def c_broadcast_lower(ctx: LowerContext):
    x = ctx.input("X")
    ax = _axis(ctx)
    root = ctx.attr("root", 0)
    ctx.set_output("Out", x if ax is None else broadcast(x, ax, root))


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


@register_op("c_allgather", infer_shape=_infer_skip)
def c_allgather_lower(ctx: LowerContext):
    x = ctx.input("X")
    ax = _axis(ctx)
    ctx.set_output("Out", x if ax is None
                   else all_gather(x, ax, axis=0, tiled=True))


@register_op("c_reducescatter", infer_shape=_infer_skip)
def c_reducescatter_lower(ctx: LowerContext):
    x = ctx.input("X")
    ax = _axis(ctx)
    ctx.set_output("Out", x if ax is None else reduce_scatter(x, ax))


@register_op("c_alltoall", infer_shape=_infer_skip)
def c_alltoall_lower(ctx: LowerContext):
    x = ctx.input("X")
    ax = _axis(ctx)
    ctx.set_output("Out", x if ax is None
                   else all_to_all(x, ax, 0, 0))
