"""Ring attention: exact attention over sequences sharded across devices.

The reference has NO sequence/context parallelism (SURVEY.md §2.8 — its
long-sequence story is LoD ragged batching); this is the TPU-native
superseding design: shard the sequence axis over a mesh axis, keep Q local,
and rotate K/V shards around the ICI ring with ``ppermute`` while
accumulating an online (flash-style) softmax — memory per chip is
O(S/p * S/p) and the K/V transfer overlaps with compute on real hardware.

Reference pattern: Liu et al., "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (public); built here on jax shard_map.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention"]

NEG_INF = -1e30


def _local_block(q, k, v, q_off, k_off, causal, scale):
    """Scores of a local [Sq,D] x [Sk,D] block with global-position causal
    masking; returns (scores [B,H,Sq,Sk])."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        S_q, S_k = q.shape[2], k.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 0) + q_off
        col = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 1) + k_off
        s = jnp.where((col > row)[None, None], NEG_INF, s)
    return s


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq", causal=False,
                   scale=None):
    """Exact attention with q, k, v [B, H, S, D] sharded on S over
    ``axis`` of ``mesh``.  Returns [B, H, S, D] with the same sharding."""
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    p = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    S = q.shape[2]
    assert S % p == 0, f"seq len {S} not divisible by mesh axis {axis}={p}"
    s_local = S // p

    spec = P(None, None, axis, None)

    def local_fn(q_l, k_l, v_l):
        # q_l/k_l/v_l: [B, H, S/p, D] local shards
        idx = jax.lax.axis_index(axis)
        q_off = idx * s_local
        B, H, Sq, D = q_l.shape
        Dv = v_l.shape[3]

        m0 = jnp.full((B, H, Sq, 1), NEG_INF, q_l.dtype)
        l0 = jnp.zeros((B, H, Sq, 1), q_l.dtype)
        acc0 = jnp.zeros((B, H, Sq, Dv), q_l.dtype)

        def body(carry, step):
            # lax.scan (not fori_loop/while) so jax.vjp can differentiate
            # the ring — training runs through this path
            m, l, acc, k_cur, v_cur = carry
            # the shard we hold at ``step`` originated at device idx-step
            src = (idx - step) % p
            k_off = src * s_local
            s = _local_block(q_l, k_cur, v_cur, q_off, k_off, causal,
                             scale)
            blk_m = jnp.max(s, axis=-1, keepdims=True)
            new_m = jnp.maximum(m, blk_m)
            # renormalize the running accumulator to the new max
            correction = jnp.exp(m - new_m)
            probs = jnp.exp(s - new_m)
            l_new = l * correction + probs.sum(-1, keepdims=True)
            acc_new = acc * correction + jnp.einsum(
                "bhqk,bhkd->bhqd", probs, v_cur)
            perm = [(j, (j + 1) % p) for j in range(p)]
            k_next = jax.lax.ppermute(k_cur, axis, perm)
            v_next = jax.lax.ppermute(v_cur, axis, perm)
            return (new_m, l_new, acc_new, k_next, v_next), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            body, (m0, l0, acc0, k_l, v_l), jnp.arange(p))
        # rows with no unmasked keys (fully-causal top rows never happen
        # since diagonal always visible) — safe divide
        return acc / jnp.maximum(l, 1e-30)

    fn = shard_map(local_fn, mesh=mesh,
                   in_specs=(spec, spec, spec), out_specs=spec,
                   check_rep=False)
    return fn(q, k, v)
