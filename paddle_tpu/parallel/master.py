"""Fault-tolerant data-task dispatch service.

TPU-native equivalent of the reference's Go master
(``go/master/service.go``): partition input files/chunks into tasks, lease
them to trainers with a timeout, recycle failed/timed-out tasks
(``checkTimeoutFunc`` :341, ``TaskFailed`` :455, ``processFailedTask``
:313, drop after ``failureMax``), and snapshot the queue state on every
mutation so a restarted master resumes where it left off (``snapshot``
:207 / ``recover`` :165 — etcd replaced by a local snapshot file; any
shared filesystem or object store works the same way).

In-process + thread-safe: multi-host tests drive it the way the Go tests
drive the in-memory store (``go/master/service_internal_test.go``).
"""

from __future__ import annotations

import json
import os
import threading
import time

from paddle_tpu.obs import trace as _trace
from paddle_tpu.obs.trace import span as _span

__all__ = ["Task", "MasterService", "partition_files",
           "MasterServer", "MasterClient", "MasterError"]

DEFAULT_TIMEOUT = 60.0
DEFAULT_FAILURE_MAX = 3
DEFAULT_REPLICA_TTL = 10.0


class Task:
    def __init__(self, task_id, chunks):
        self.id = task_id
        self.chunks = list(chunks)   # opaque work units (paths, ranges...)
        self.failures = 0
        self.epoch = 0               # lease epoch; stale reports rejected

    def to_dict(self):
        return {"id": self.id, "chunks": self.chunks,
                "failures": self.failures, "epoch": self.epoch}

    @staticmethod
    def from_dict(d):
        t = Task(d["id"], d["chunks"])
        t.failures = d["failures"]
        t.epoch = d["epoch"]
        return t


def partition_files(paths, chunks_per_task=1):
    """Files -> tasks (reference ``partition`` in service.go)."""
    tasks = []
    buf = []
    for p in sorted(paths):
        buf.append(p)
        if len(buf) == chunks_per_task:
            tasks.append(Task(len(tasks), buf))
            buf = []
    if buf:
        tasks.append(Task(len(tasks), buf))
    return tasks


class MasterService:
    def __init__(self, tasks=None, timeout=DEFAULT_TIMEOUT,
                 failure_max=DEFAULT_FAILURE_MAX, snapshot_path=None,
                 heartbeat_timeout=None, replica_ttl=DEFAULT_REPLICA_TTL):
        self._lock = threading.Lock()
        self.timeout = timeout
        self.failure_max = failure_max
        self.heartbeat_timeout = heartbeat_timeout
        self.replica_ttl = replica_ttl
        # serving-fleet discovery: replica_id -> lease record.  Leases
        # are deliberately ephemeral (never snapshotted): a restarted
        # master knows nothing about replica health, so replicas simply
        # re-register on their next heartbeat cycle.
        self._replicas = {}
        self.snapshot_path = snapshot_path
        self.todo = list(tasks or [])
        self.pending = {}            # task_id -> (Task, deadline)
        self.done = []
        self.failed_drop = []        # exceeded failure_max
        self._lease_owner = {}       # task_id -> trainer_id (when known)
        self._trainer_seen = {}      # trainer_id -> last heartbeat time
        # only trainers that OPTED IN by heartbeating are subject to
        # heartbeat eviction — a trainer that merely passes trainer_id
        # to get_task must not be declared dead for processing a task
        # longer than heartbeat_timeout
        self._heartbeaters = set()
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()
        else:
            self._snapshot()

    # -- client API (reference GetTask/TaskFinished/TaskFailed) ------------
    def get_task(self, trainer_id=None):
        """Lease a task; returns None when nothing is currently available
        (caller retries — tasks may return via timeout)."""
        with self._lock:
            if trainer_id is not None:
                self._trainer_seen[trainer_id] = time.time()
            self._requeue_timeouts()
            if not self.todo:
                return None
            task = self.todo.pop(0)
            task.epoch += 1
            self.pending[task.id] = (task, time.time() + self.timeout)
            if trainer_id is not None:
                self._lease_owner[task.id] = trainer_id
            self._snapshot()
            return task

    def heartbeat(self, trainer_id):
        """Trainer liveness ping.  With ``heartbeat_timeout`` set, leases
        held by a trainer that stops pinging are reclaimed promptly (at
        the next queue mutation) instead of waiting out the full lease
        timeout — the reference leaned solely on etcd lease TTLs here."""
        with self._lock:
            self._trainer_seen[trainer_id] = time.time()
            self._heartbeaters.add(trainer_id)
            self._requeue_timeouts()
            return True

    def task_finished(self, task_id, epoch=None):
        with self._lock:
            entry = self.pending.get(task_id)
            if entry is None:
                return False
            task, _ = entry
            if epoch is not None and epoch != task.epoch:
                return False  # stale lease report: current lease untouched
            del self.pending[task_id]
            self._lease_owner.pop(task_id, None)
            task.failures = 0  # reference: NumFailure resets on success
            self.done.append(task)
            self._snapshot()
            return True

    def task_failed(self, task_id, epoch=None):
        with self._lock:
            entry = self.pending.get(task_id)
            if entry is None:
                return False
            task, _ = entry
            if epoch is not None and epoch != task.epoch:
                return False  # stale lease report: current lease untouched
            del self.pending[task_id]
            self._lease_owner.pop(task_id, None)
            self._process_failed(task)
            self._snapshot()
            return True

    def all_done(self):
        with self._lock:
            self._requeue_timeouts()
            return not self.todo and not self.pending

    def reset_pass(self):
        """Re-seed the queue for a new data pass: finished tasks go back
        to todo (reference master restarts passes the same way when the
        dataset drains).  Call only when all_done() — a coordinator (e.g.
        cloud_reader's pass loop) drives this."""
        with self._lock:
            if self.todo or self.pending:
                return False
            # reference service.go: Todo = Done + Failed for the new pass
            for t in self.failed_drop:
                t.failures = 0
            self.todo = self.done + self.failed_drop
            self.done = []
            self.failed_drop = []
            self._snapshot()
            return True

    def stats(self):
        with self._lock:
            now = time.time()
            return {"todo": len(self.todo), "pending": len(self.pending),
                    "done": len(self.done),
                    "dropped": len(self.failed_drop),
                    "trainers": len(self._trainer_seen),
                    # expired-but-unpruned leases are NOT live replicas
                    "replicas": sum(1 for r in self._replicas.values()
                                    if r["expires"] >= now)}

    # -- serving-fleet discovery (lease-based replica health) -------------
    #
    # The trainer-side lease machinery above re-aimed at inference: a
    # serving replica registers its address on startup, renews the lease
    # on every heartbeat, and is dropped from the routing table the
    # moment the lease expires (a silent replica IS a dead replica, the
    # router never has to probe it).

    def register_replica(self, replica_id, addr, ttl=None, meta=None):
        """Enroll (or re-enroll) a serving replica at ``addr`` with a
        lease of ``ttl`` seconds.  Returns the lease terms; the replica
        must :meth:`renew_replica` within ``ttl`` or it is dropped from
        :meth:`list_replicas`.  Re-registering bumps the lease epoch
        (late renews from a previous incarnation are then rejected)."""
        ttl = float(ttl if ttl is not None else self.replica_ttl)
        if ttl <= 0:
            raise ValueError(f"replica ttl must be > 0, got {ttl}")
        with self._lock:
            prev = self._replicas.get(replica_id)
            epoch = (prev["epoch"] + 1) if prev else 1
            self._replicas[replica_id] = {
                "id": replica_id, "addr": str(addr),
                "meta": dict(meta or {}), "ttl": ttl,
                "expires": time.time() + ttl, "epoch": epoch,
            }
            return {"epoch": epoch, "ttl": ttl}

    def renew_replica(self, replica_id, epoch=None):
        """Heartbeat-renew a replica lease.  Returns False when the
        lease is unknown, already expired, or from a stale epoch — the
        replica is (or just became) invisible to the router and must
        re-register before taking traffic again."""
        from paddle_tpu.fault import chaos
        try:
            # armed drill: the master force-expires this lease as if the
            # TTL ran out — the replica sees lease_lost while perfectly
            # alive, exactly the split-brain /readyz must surface
            chaos.fire("master.lease.expire", replica_id=replica_id)
        except chaos.FaultInjected:
            with self._lock:
                self._replicas.pop(replica_id, None)
            return False
        with self._lock:
            rec = self._replicas.get(replica_id)
            now = time.time()
            if rec is None or rec["expires"] < now or \
                    (epoch is not None and epoch != rec["epoch"]):
                if rec is not None and rec["expires"] < now:
                    del self._replicas[replica_id]
                return False
            rec["expires"] = now + rec["ttl"]
            return True

    def deregister_replica(self, replica_id):
        """Release a replica lease explicitly (the drain path of a
        rolling restart: the router stops routing BEFORE the replica
        stops accepting).  Returns False when the lease was already
        gone."""
        with self._lock:
            return self._replicas.pop(replica_id, None) is not None

    def list_replicas(self):
        """Live replicas (expired leases pruned), for router discovery:
        ``[{id, addr, meta, epoch, expires_in}, ...]``."""
        with self._lock:
            now = time.time()
            for rid in [rid for rid, rec in self._replicas.items()
                        if rec["expires"] < now]:
                del self._replicas[rid]
            return [{"id": rec["id"], "addr": rec["addr"],
                     "meta": dict(rec["meta"]), "epoch": rec["epoch"],
                     "expires_in": round(rec["expires"] - now, 3)}
                    for rec in self._replicas.values()]

    # -- internals ---------------------------------------------------------
    def _process_failed(self, task):
        task.failures += 1
        if task.failures >= self.failure_max:
            self.failed_drop.append(task)
        else:
            self.todo.append(task)

    def _requeue_timeouts(self):
        now = time.time()
        expired = [tid for tid, (_, dl) in self.pending.items() if dl < now]
        if self.heartbeat_timeout is not None:
            # leases of trainers that stopped heartbeating are reclaimed
            # without waiting out the full lease timeout
            dead = {t for t in self._heartbeaters
                    if now - self._trainer_seen.get(t, now)
                    > self.heartbeat_timeout}
            expired += [tid for tid, owner in self._lease_owner.items()
                        if owner in dead and tid not in expired
                        and tid in self.pending]
            for t in dead:
                self._trainer_seen.pop(t, None)
                self._heartbeaters.discard(t)
        # registry hygiene: trainer ids that neither hold leases nor
        # heartbeat within a generous horizon are forgotten, so a
        # long-lived master serving elastically scaled trainers (fresh
        # ids every restart) doesn't grow without bound
        horizon = max(self.heartbeat_timeout or 0.0, 10.0 * self.timeout)
        owners = set(self._lease_owner.values())
        for tid in [t for t, seen in self._trainer_seen.items()
                    if now - seen > horizon and t not in owners]:
            self._trainer_seen.pop(tid, None)
            self._heartbeaters.discard(tid)
        for tid in expired:
            task, _ = self.pending.pop(tid)
            self._lease_owner.pop(tid, None)
            # bump the epoch at eviction so a LATE task_finished /
            # task_failed from the evicted holder is rejected even if it
            # lands before the task is re-leased
            task.epoch += 1
            self._process_failed(task)
        if expired:
            self._snapshot()

    def _snapshot(self):
        if not self.snapshot_path:
            return
        state = {
            "todo": [t.to_dict() for t in self.todo],
            "pending": [t.to_dict() for t, _ in self.pending.values()],
            "done": [t.to_dict() for t in self.done],
            "dropped": [t.to_dict() for t in self.failed_drop],
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)

    def _recover(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        # leases don't survive a master restart: pending -> todo
        self.todo = [Task.from_dict(d) for d in state["todo"]] + \
                    [Task.from_dict(d) for d in state["pending"]]
        self.pending = {}
        self.done = [Task.from_dict(d) for d in state["done"]]
        self.failed_drop = [Task.from_dict(d) for d in state["dropped"]]


# ---------------------------------------------------------------------------
# network layer: the go/cmd/master binary + trainer-side client analog
# (reference ``go/cmd/master/master.go`` serving Go net/rpc;
# ``python/paddle/v2/master/client.py`` ctypes client).  JSON-lines over
# TCP: {"method": ..., "params": {...}} -> {"result": ...}.
# ---------------------------------------------------------------------------

import socket
import socketserver


class _MasterRPCHandler(socketserver.StreamRequestHandler):
    def handle(self):
        svc = self.server.service
        for line in self.rfile:
            try:
                req = json.loads(line)
                method = req.get("method")
                params = req.get("params") or {}
                # trace-context hop: the caller's trace id rides the
                # frame as _trace — this RPC's server-side span joins
                # the calling trainer's timeline
                caller_trace = params.pop("_trace", None)
                with _trace.trace_context(caller_trace), \
                        _span("master.serve", method=str(method)):
                    result = self._dispatch(svc, method, params)
                resp = {"result": result}
            except Exception as e:  # surface errors to the client
                resp = {"error": str(e)}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()

    @staticmethod
    def _dispatch(svc, method, params):
        if method == "get_task":
            t = svc.get_task(params.get("trainer_id"))
            return t.to_dict() if t is not None else None
        if method == "heartbeat":
            return svc.heartbeat(params["trainer_id"])
        if method == "task_finished":
            return svc.task_finished(params["task_id"],
                                     params.get("epoch"))
        if method == "task_failed":
            return svc.task_failed(params["task_id"],
                                   params.get("epoch"))
        if method == "all_done":
            return svc.all_done()
        if method == "reset_pass":
            return svc.reset_pass()
        if method == "stats":
            return svc.stats()
        if method == "register_replica":
            return svc.register_replica(params["replica_id"],
                                        params["addr"],
                                        ttl=params.get("ttl"),
                                        meta=params.get("meta"))
        if method == "renew_replica":
            return svc.renew_replica(params["replica_id"],
                                     epoch=params.get("epoch"))
        if method == "deregister_replica":
            return svc.deregister_replica(params["replica_id"])
        if method == "list_replicas":
            return svc.list_replicas()
        if method == "ping":
            return "pong"
        raise ValueError(f"unknown method {method!r}")


class MasterServer:
    """Serve a MasterService over TCP (go master binary analog)."""

    def __init__(self, service, host="127.0.0.1", port=0):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Srv((host, port), _MasterRPCHandler)
        self._server.service = service
        self.addr = self._server.server_address

    def serve_forever(self):
        self._server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class MasterError(RuntimeError):
    """The master executed the request and reported an error (NOT a
    transport failure — never retried)."""


class MasterClient:
    """Trainer-side client (reference ``go/pserver/client`` C ABI +
    ``python/paddle/v2/master/client.py``).

    Transport failures (connection reset, master restart, timeout) are
    retried under ``retry`` (a :class:`paddle_tpu.fault.RetryPolicy`;
    default ``DEFAULT_RPC_POLICY``) with a fresh connection per attempt,
    so a flaky or briefly-restarting master no longer kills the trainer.
    Re-sent requests are at-least-once safe: every mutating method is
    idempotent under the lease epoch (a duplicate ``task_finished`` /
    ``task_failed`` returns False, a re-sent ``get_task`` at worst
    double-leases a task whose first lease times out and requeues).
    """

    def __init__(self, addr, timeout=30.0, retry=None, trainer_id=None):
        from paddle_tpu.fault.retry import (DEFAULT_RPC_POLICY,
                                            parse_hostport)
        self._addr = parse_hostport(addr)
        self._timeout = timeout
        self._retry = retry or DEFAULT_RPC_POLICY
        self.trainer_id = trainer_id
        self._lock = threading.Lock()
        self._sock = None
        self._rfile = None
        self._hb_stop = None
        self._closed = False
        # connection is lazy: the first _call dials under the retry
        # policy, so constructing a client while the master is briefly
        # down (trainer resume during master restart) is safe

    def _connect(self):
        if self._closed:
            raise RuntimeError("MasterClient is closed")
        self._drop_connection()
        host, port = self._addr
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=self._timeout)
        self._rfile = self._sock.makefile("r")

    def _drop_connection(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None

    def _call(self, method, **params):
        from paddle_tpu.fault import chaos
        rid = _trace.current_trace_id()
        if rid is not None:
            # caller's trace id crosses the process boundary in-frame;
            # the master's handler spans join this trace
            params["_trace"] = rid

        def attempt():
            chaos.fire("master.rpc", method=method)
            with self._lock:
                if self._sock is None:
                    self._connect()
                try:
                    msg = json.dumps({"method": method,
                                      "params": params}) + "\n"
                    self._sock.sendall(msg.encode())
                    line = self._rfile.readline()
                    if not line:  # server closed mid-request
                        raise ConnectionError("master closed connection")
                    return json.loads(line)
                except OSError:
                    # a dead stream can't be reused: reconnect on the
                    # next attempt
                    self._drop_connection()
                    raise
                except ValueError as e:
                    # garbled/truncated frame — same remedy as a reset
                    self._drop_connection()
                    raise ConnectionError(f"garbled master reply: {e}") \
                        from e

        with _span("master.rpc", method=method):
            resp = self._retry.call(attempt)
        if "error" in resp:
            raise MasterError(f"master: {resp['error']}")
        return resp["result"]

    def get_task(self):
        d = self._call("get_task", trainer_id=self.trainer_id)
        return Task.from_dict(d) if d is not None else None

    def heartbeat(self):
        if self.trainer_id is None:
            raise ValueError("heartbeat requires a trainer_id")
        return self._call("heartbeat", trainer_id=self.trainer_id)

    def start_heartbeats(self, interval=5.0):
        """Send heartbeats from a daemon thread every ``interval``
        seconds (enrolls this trainer in heartbeat-based lease
        reclamation on the master).  Stops on :meth:`close`."""
        if self.trainer_id is None:
            raise ValueError("heartbeats require a trainer_id")
        if self._hb_stop is not None:
            return
        stop = threading.Event()   # captured: immune to close() racing
        self._hb_stop = stop       # the attribute back to None

        def beat():
            while not stop.wait(interval):
                try:
                    self.heartbeat()
                except Exception:
                    pass  # transient (retried already) — or closed

        threading.Thread(target=beat, daemon=True).start()

    def task_finished(self, task_id, epoch=None):
        return self._call("task_finished", task_id=task_id, epoch=epoch)

    def task_failed(self, task_id, epoch=None):
        return self._call("task_failed", task_id=task_id, epoch=epoch)

    def all_done(self):
        return self._call("all_done")

    def reset_pass(self):
        return self._call("reset_pass")

    def stats(self):
        return self._call("stats")

    # -- serving-fleet discovery ------------------------------------------
    def register_replica(self, replica_id, addr, ttl=None, meta=None):
        return self._call("register_replica", replica_id=replica_id,
                          addr=addr, ttl=ttl, meta=meta)

    def renew_replica(self, replica_id, epoch=None):
        return self._call("renew_replica", replica_id=replica_id,
                          epoch=epoch)

    def deregister_replica(self, replica_id):
        return self._call("deregister_replica", replica_id=replica_id)

    def list_replicas(self):
        return self._call("list_replicas")

    def close(self):
        self._closed = True   # an in-flight retry can no longer redial
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None
        self._drop_connection()
