"""ParallelExecutor: data-parallel training over the device mesh.

Reference semantics (``python/paddle/fluid/parallel_executor.py:23`` over
``paddle/fluid/framework/parallel_executor.cc:53``): replicate the program
per GPU, scatter the batch, all-reduce gradients with NCCL, keep parameters
replicated.

TPU-native realization: the SAME lowered step function as ``Executor``,
jit-compiled with explicit shardings over a ``Mesh`` —
  feeds            -> PartitionSpec('data', ...)   (batch split over ICI)
  params/state     -> PartitionSpec()              (replicated), or a
                      tensor-parallel spec from ``param_shardings``
  written state    -> same as its input sharding (forces XLA to insert the
                      gradient all-reduce / reduce-scatter)
No SSA graph, no op handles, no per-device scopes: GSPMD partitions the one
XLA computation and the collectives ride the ICI mesh.

Tensor parallelism (the reference has only layer-device placement,
``ParallelNeuralNetwork.h``): pass ``param_shardings`` as a list of
``(regex, PartitionSpec)`` rules; the first rule matching a state var's
name gives its spec, and GSPMD propagates through the computation
(Megatron-style column/row splits come from the specs alone — see
``paddle_tpu.models.transformer.tp_shardings``).
"""

from __future__ import annotations

import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu import framework
from paddle_tpu.executor import Executor, _CompiledBlock, lower_block
from paddle_tpu.framework import default_main_program
from paddle_tpu.scope import global_scope
from paddle_tpu.parallel.mesh import default_mesh, DATA_AXIS

__all__ = ["ParallelExecutor"]


class ParallelExecutor(Executor):
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, num_threads=None, mesh=None,
                 batch_axis=0, param_shardings=None, zero=False):
        super().__init__()
        self.mesh = mesh if mesh is not None else default_mesh()
        self.loss_name = loss_name
        self.batch_axis = batch_axis
        self._main_program = main_program
        # [(compiled regex, PartitionSpec)] — first match wins
        self.param_shardings = [(re.compile(pat), spec)
                                for pat, spec in (param_shardings or [])]
        # ZeRO optimizer-state sharding: partition the accumulators over
        # the data axis (params stay replicated).  The plan is emitted
        # as IR-level sharding facts and PROVED by the PTA016/PTA017
        # pass here — before anything compiles, let alone runs.  User
        # param_shardings rules keep precedence (first match wins), so
        # TP-ruled state never double-shards.
        self.zero_plan = None
        if zero:
            from paddle_tpu.parallel.zero import zero_plan
            axis = zero if isinstance(zero, str) else DATA_AXIS
            program = main_program or default_main_program()
            skip = (lambda name: any(pat.search(name) for pat, _ in
                                     self.param_shardings)) \
                if self.param_shardings else None
            plan = zero_plan(program, self.mesh, axis=axis, skip=skip)
            plan.verify()
            self.zero_plan = plan
            self.param_shardings += [(re.compile(pat), spec)
                                     for pat, spec in plan.rules()]
        if share_vars_from is not None:
            pass  # scope is global; parity no-op

    def _state_sharding(self, name, shape=None):
        for pat, spec in self.param_shardings:
            if pat.search(name):
                if shape is None or _spec_fits(spec, shape, self.mesh):
                    return NamedSharding(self.mesh, spec)
                break  # rule matched but shape can't shard (e.g. the
                # scalar beta-pow accumulator of a sharded bias)
        return NamedSharding(self.mesh, P())

    @property
    def device_count(self):
        return int(np.prod(self.mesh.devices.shape))

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            program=None, return_numpy=True, scope=None, sentinel=None):
        feed = feed if feed is not None else (feed_dict or {})
        program = program or self._main_program or default_main_program()
        return super().run(program=program, feed=feed,
                           fetch_list=fetch_list, scope=scope,
                           return_numpy=return_numpy, sentinel=sentinel)

    # -- sharding-aware compile ----------------------------------------
    def _get_compiled(self, program, block, feed_arrays, fetch_names, scope,
                      donate=True):
        from paddle_tpu.executor import _freeze_lod
        feed_lods = tuple(sorted(
            (n, _freeze_lod(scope.find_lod(n))) for n in feed_arrays
            if scope.find_lod(n) is not None))
        from paddle_tpu import profiler as _profiler
        sig = ("pexe", id(program), program._version, block.idx,
               tuple(sorted((n, str(a.dtype), a.shape)
                            for n, a in feed_arrays.items())),
               feed_lods,
               fetch_names, donate)
        if sig in self._cache:
            self._cache[sig] = self._cache.pop(sig)  # LRU bump
            _profiler.runtime_metrics.inc("jit_cache.hits")
            return self._cache[sig]
        # count the sharded-wrapper miss HERE: super() below also counts
        # its base-signature lookup, and that one can legitimately hit
        # while this level re-jits (each parallel program holds two
        # cache entries — base step + sharded wrapper)
        _profiler.runtime_metrics.inc("jit_cache.misses")

        base = super()._get_compiled(program, block, feed_arrays,
                                     fetch_names, scope, donate=donate)
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        data_size = dict(zip(mesh.axis_names,
                             mesh.devices.shape)).get(DATA_AXIS, 1)

        def feed_sharding(name, arr):
            # batch-shard data along the batch axis over the 'data' mesh
            # axis when divisible
            if arr.ndim > 0 and data_size > 1 and \
                    arr.shape[self.batch_axis] % data_size == 0:
                spec = [None] * arr.ndim
                spec[self.batch_axis] = DATA_AXIS
                return NamedSharding(mesh, P(*spec))
            return repl

        def shape_of(n):
            v = scope.find_var(n)
            return getattr(v, "shape", None) if v is not None else None

        state_shardings = {n: self._state_sharding(n, shape_of(n))
                           for n in (*base.ro_names, *base.inout_names)}
        out_state_names = list(dict.fromkeys(
            list(base.inout_names) + _written_persistables(block)))
        for n in out_state_names:
            state_shardings.setdefault(
                n, self._state_sharding(n, shape_of(n)))

        in_shardings = (
            {n: feed_sharding(n, a) for n, a in feed_arrays.items()},
            {n: state_shardings[n] for n in base.ro_names},
            {n: state_shardings[n] for n in base.inout_names},
            repl,  # rng key
        )
        training = not program._is_inference
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        lod_map = {}
        for n, lod in feed_lods:
            if isinstance(lod, tuple) and lod and lod[0] == "dyn":
                lod_map[n] = DynLoD(n + SPLITS_SUFFIX, lod[1], lod[2])
            else:
                lod_map[n] = [list(level) for level in lod]

        def step(feeds, ro_state, inout_state, rng_key):
            env = {}
            env.update(feeds)
            env.update(ro_state)
            env.update(inout_state)
            aux = {"rng_counter": 0, "scope": scope,
                   "lower_block": lower_block, "mesh": mesh,
                   "lod": dict(lod_map),
                   # opt-pipeline fact (see Executor._prepare): key-
                   # free ops skip their per-op fold_in at trace time
                   "rng_plan": True
                   if getattr(program, "_opt_rng_plan", False)
                   else None}
            lower_block(block, env, rng_key, training, aux)
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in out_state_names if n in env}
            return fetches, new_state

        # trace once abstractly to learn which state names actually get
        # produced, so out_shardings matches the returned dict exactly
        out_shardings = (None, {n: state_shardings[n]
                                for n in out_state_names})
        jitted = jax.jit(step, in_shardings=in_shardings,
                         out_shardings=out_shardings,
                         donate_argnums=(2,) if donate else ())
        from paddle_tpu.obs import perf as _perf
        if _perf.capture_enabled():
            # cost/memory capture on the sharded executable: the
            # recorded FLOPs cover the WHOLE mesh, so note_step divides
            # by device_count when deriving the live MFU gauge
            jitted = _perf.instrument_jit(
                jitted, label=_perf.jit_label(
                    feed_arrays, fetch_names,
                    tag=f"mesh{tuple(mesh.devices.shape)}"))
        feed_shardings = in_shardings[0]

        def place(a, sharding):
            # skip the device_put dispatch when already placed (state is
            # sharded after the first step; only feeds arrive fresh)
            if getattr(a, "sharding", None) == sharding:
                return a
            return jax.device_put(a, sharding)

        def fn(feeds, ro_state, inout_state, rng_key):
            feeds = {n: place(a, feed_shardings[n])
                     for n, a in feeds.items()}
            ro_state = {n: place(a, state_shardings[n])
                        for n, a in ro_state.items()}
            inout_state = {n: place(a, state_shardings[n])
                           for n, a in inout_state.items()}
            rng_key = jax.device_put(rng_key, repl)
            return jitted(feeds, ro_state, inout_state, rng_key)

        compiled = _CompiledBlock(fn, base.feed_names, base.ro_names,
                                  base.inout_names, tuple(fetch_names), True)
        compiled.donated = donate
        compiled.perf = getattr(jitted, "perf", None)
        self._cache_insert(sig, compiled)
        return compiled

    def _feed_device(self):
        return None


def _spec_fits(spec, shape, mesh):
    """True when every sharded dim of ``shape`` divides evenly by the
    product of its mesh axis sizes."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if len(spec) > len(shape):
        return False
    for dim, ax in zip(shape, tuple(spec)):
        if ax is None:
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        k = 1
        for a in axes:
            k *= sizes.get(a, 1)
        if dim is None or dim < 0 or dim % k:
            return False
    return True


def _written_persistables(block):
    from paddle_tpu.executor import _SKIP_OPS
    out = []
    for op in block.ops:
        if op.type in _SKIP_OPS:  # reader vars hold host objects, not state
            continue
        for n in op.output_arg_names:
            try:
                var = block.var(n)
            except KeyError:
                continue
            if var.persistable and n not in out:
                out.append(n)
    return out
