"""DistributeTranspiler — program partitioning over the mesh.

The reference (``python/paddle/fluid/distribute_transpiler.py:138``)
rewrites the program into a trainer program (send/recv grads over gRPC) and
per-pserver programs (ListenAndServ + optimize blocks), splitting parameters
into round-robin blocks (``distributed_splitter.py:37``).

On TPU there is no parameter server: gradients are all-reduced over the ICI
mesh inside the one compiled step (see ``ParallelExecutor``), and parameter
*sharding* (the pserver's raison d'être — params too big for one device)
is expressed as PartitionSpecs consumed by the executor.  This class keeps
the transpiler-shaped API and produces a ``DistributedSpec``: the mapping
param name -> PartitionSpec, plus the trainer program (unchanged ops, since
collectives are implicit in XLA's SPMD partitioning).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from paddle_tpu.framework import default_main_program, default_startup_program
from paddle_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

__all__ = ["DistributeTranspiler", "DistributedSpec",
           "round_robin_split", "hash_name_split"]


class DistributedSpec:
    """Where each parameter lives on the mesh (replaces the reference's
    param-block -> pserver-endpoint placement map)."""

    def __init__(self):
        self.param_specs = {}   # name -> PartitionSpec
        self.grad_specs = {}
        self.placement = {}     # name -> home shard (reference eplist)
        self.num_shards = 1

    def spec_for(self, name):
        return self.param_specs.get(name, P())


def round_robin_split(params, num_shards):
    """reference ``distributed_splitter.py:37`` round_robin."""
    shards = [[] for _ in range(num_shards)]
    for i, p in enumerate(params):
        shards[i % num_shards].append(p)
    return shards


def hash_name_split(params, num_shards):
    """reference ``distributed_splitter.py:16`` hash_name: stable
    name-hash placement (md5, not Python's salted ``hash``)."""
    import hashlib
    shards = [[] for _ in range(num_shards)]
    for p in params:
        name = p.name if hasattr(p, "name") else str(p)
        h = int(hashlib.md5(name.encode()).hexdigest()[:8], 16)
        shards[h % num_shards].append(p)
    return shards


class DistributeTranspiler:
    """API parity with reference ``DistributeTranspiler:138``."""

    def __init__(self):
        self.spec = DistributedSpec()
        self._program = None
        self._startup = None

    def transpile(self, trainer_id=0, program=None, pservers="", trainers=1,
                  split_method=round_robin_split, startup_program=None,
                  shard_params=False, mesh_axis=MODEL_AXIS, mesh=None):
        """Record the distribution plan.

        ``pservers``/``trainers`` are accepted for API parity; the TPU plan
        ignores endpoints (no gRPC) and instead decides, per parameter,
        whether to shard it over ``mesh_axis`` (the pserver-sharding analog)
        or replicate it.  ``mesh``: optional Mesh — when given, the
        post-transpile plan verification also proves axis existence and
        divisibility against the actual axis sizes.

        Sparse path: the reference distributes ``is_distributed`` embedding
        tables across pservers and rewrites lookups into ``prefetch_op``
        RPCs (``distribute_transpiler.py:138`` sparse branch,
        ``operators/prefetch_op.cc``).  Here such tables are sharded over
        the mesh's model axis on dim 0 (the vocab dim); GSPMD turns the
        in-graph gather into the all-to-all/all-gather exchange that
        prefetch performed by hand, so no program rewrite is needed.
        """
        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        num_shards = max(len(pservers.split(",")) if pservers else 1, 1)
        self.spec.num_shards = num_shards
        block = self._program.global_block()

        # distributed embedding tables (the pserver sparse-table analog)
        dist_tables = set()
        for op in block.ops:
            if op.type == "lookup_table" and op.attr("is_distributed", False):
                dist_tables.add(op.input("W")[0])

        params = block.all_parameters()
        # the reference's eplist: split_method (round_robin / hash_name)
        # assigns each parameter a home shard.  Under GSPMD that degree
        # of freedom is only bookkeeping — tensors are either sharded
        # over the axis or replicated — but the placement map is kept
        # for parity/debugging (``placement()``), and the reference's
        # PARAM-BLOCK SPLITTING (distributed_splitter cuts big params
        # into ~8k-element blocks spread over pservers) is subsumed by
        # sharding dim 0 over ``mesh_axis``: GSPMD tiles the parameter
        # across devices exactly as the block split spread it across
        # pservers, with the all-reduce/all-gather exchange implicit.
        shards = split_method(params, num_shards)
        self.spec.placement = {
            p.name: k for k, part in enumerate(shards) for p in part}
        for p in params:
            first_dim_shards = (p.shape and len(p.shape) >= 1 and
                                p.shape[0] is not None and p.shape[0] > 0)
            if p.name in dist_tables and first_dim_shards:
                self.spec.param_specs[p.name] = P(mesh_axis, None)
            elif shard_params and first_dim_shards \
                    and p.shape[0] % num_shards == 0:
                # shard the first (output/vocab) dim — the same dim the
                # reference splits into pserver blocks
                self.spec.param_specs[p.name] = P(mesh_axis)
            else:
                self.spec.param_specs[p.name] = P()
        # post-transpile contract (paddle_tpu.analysis): the plan is
        # recorded against a structurally verified program, and the
        # plan ITSELF is verified — every declared placement must be
        # well-formed against the program (and the mesh, when given)
        # and propagate without a provable param/grad disagreement
        from paddle_tpu.analysis import (AnalysisResult,
                                         check_distributed_spec,
                                         verify_transpiled)
        verify_transpiled(self._program, where="distribute_transpiler")
        mesh_axes = None
        if mesh is not None:
            mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        AnalysisResult(check_distributed_spec(
            self._program, self.spec, mesh_axes=mesh_axes)) \
            .raise_on_errors(where="distribute_transpiler")
        return self

    def placement(self):
        """name -> home-shard id, the reference's ``eplist`` analog."""
        return dict(self.spec.placement)

    def param_shardings(self):
        """The plan as ``ParallelExecutor(param_shardings=...)`` rules:
        exact-name regexes, non-replicated params only."""
        import re as _re
        return [(f"^{_re.escape(name)}$", spec)
                for name, spec in self.spec.param_specs.items()
                if tuple(spec) != ()]

    def get_trainer_program(self):
        """On TPU the trainer program IS the program: collectives are
        implicit (reference :311 strips optimize ops instead)."""
        return self._program

    def get_pserver_program(self, endpoint=None):
        """No parameter server exists; return the program so existing
        call-sites keep working, with the spec describing placement
        (reference :319 builds a ListenAndServ program)."""
        return self._program

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return self._startup
