"""DistributeTranspiler — program partitioning over the mesh.

The reference (``python/paddle/fluid/distribute_transpiler.py:138``)
rewrites the program into a trainer program (send/recv grads over gRPC) and
per-pserver programs (ListenAndServ + optimize blocks), splitting parameters
into round-robin blocks (``distributed_splitter.py:37``).

On TPU there is no parameter server: gradients are all-reduced over the ICI
mesh inside the one compiled step (see ``ParallelExecutor``), and parameter
*sharding* (the pserver's raison d'être — params too big for one device)
is expressed as PartitionSpecs consumed by the executor.  This class keeps
the transpiler-shaped API and produces a ``DistributedSpec``: the mapping
param name -> PartitionSpec, plus the trainer program (unchanged ops, since
collectives are implicit in XLA's SPMD partitioning).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from paddle_tpu.framework import default_main_program, default_startup_program
from paddle_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

__all__ = ["DistributeTranspiler", "DistributedSpec", "round_robin_split"]


class DistributedSpec:
    """Where each parameter lives on the mesh (replaces the reference's
    param-block -> pserver-endpoint placement map)."""

    def __init__(self):
        self.param_specs = {}   # name -> PartitionSpec
        self.grad_specs = {}
        self.num_shards = 1

    def spec_for(self, name):
        return self.param_specs.get(name, P())


def round_robin_split(params, num_shards):
    """reference ``distributed_splitter.py:37`` round_robin."""
    shards = [[] for _ in range(num_shards)]
    for i, p in enumerate(params):
        shards[i % num_shards].append(p)
    return shards


class DistributeTranspiler:
    """API parity with reference ``DistributeTranspiler:138``."""

    def __init__(self):
        self.spec = DistributedSpec()
        self._program = None
        self._startup = None

    def transpile(self, trainer_id=0, program=None, pservers="", trainers=1,
                  split_method=round_robin_split, startup_program=None,
                  shard_params=False, mesh_axis=MODEL_AXIS):
        """Record the distribution plan.

        ``pservers``/``trainers`` are accepted for API parity; the TPU plan
        ignores endpoints (no gRPC) and instead decides, per parameter,
        whether to shard it over ``mesh_axis`` (the pserver-sharding analog)
        or replicate it.
        """
        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        num_shards = max(len(pservers.split(",")) if pservers else 1, 1)
        self.spec.num_shards = num_shards
        params = self._program.global_block().all_parameters()
        for p in params:
            if shard_params and p.shape and p.shape[0] % num_shards == 0 \
                    and len(p.shape) >= 1:
                # shard the first (output/vocab) dim — the same dim the
                # reference splits into pserver blocks
                self.spec.param_specs[p.name] = P(mesh_axis)
            else:
                self.spec.param_specs[p.name] = P()
        return self

    def get_trainer_program(self):
        """On TPU the trainer program IS the program: collectives are
        implicit (reference :311 strips optimize ops instead)."""
        return self._program

    def get_pserver_program(self, endpoint=None):
        """No parameter server exists; return the program so existing
        call-sites keep working, with the spec describing placement
        (reference :319 builds a ListenAndServ program)."""
        return self._program

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return self._startup
