"""v2 activation objects (reference ``python/paddle/v2/activation.py`` ->
``trainer_config_helpers/activations.py``)."""


class BaseActivation:
    name = None


def _mk(name_, act):
    cls = type(name_, (BaseActivation,), {"name": act})
    return cls


Tanh = _mk("Tanh", "tanh")
Sigmoid = _mk("Sigmoid", "sigmoid")
Softmax = _mk("Softmax", "softmax")
Relu = _mk("Relu", "relu")
BRelu = _mk("BRelu", "brelu")
SoftRelu = _mk("SoftRelu", "soft_relu")
STanh = _mk("STanh", "stanh")
Linear = _mk("Linear", None)
Identity = Linear
Exp = _mk("Exp", "exp")
Log = _mk("Log", "log")
Square = _mk("Square", "square")
SequenceSoftmax = _mk("SequenceSoftmax", "sequence_softmax")
