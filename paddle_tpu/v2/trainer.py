"""v2 SGD trainer loop (reference ``python/paddle/v2/trainer.py:37``:
SGD.train drives GradientMachine.forwardBackward; here it appends the
optimizer to the cost's program once and drives the XLA Executor)."""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.v2 import event as v2_event
from paddle_tpu.v2 import data_type as dt

__all__ = ["SGD"]


def _metric_value(v):
    """Scalar metrics come back as floats; vector evaluator outputs
    (column_sum, precision_recall) pass through as arrays."""
    arr = np.asarray(v)
    return float(arr.reshape(())) if arr.size == 1 else arr


def _feed_converter(var, column):
    """Convert a v2 minibatch column per the data layer's input type."""
    t = getattr(var, "v2_input_type", None)
    if t is not None and t.type == dt.DataType.Index:
        if t.seq_type:
            flat, splits = [], [0]
            for seq in column:
                flat.extend(int(v) for v in seq)
                splits.append(len(flat))
            return (np.asarray(flat, "int64").reshape(-1, 1), [splits])
        return np.asarray([[int(v)] for v in column], "int64")
    if t is not None and t.seq_type:
        flat, splits = [], [0]
        for seq in column:
            flat.extend(seq)
            splits.append(len(flat))
        return (np.asarray(flat, "float32"), [splits])
    return np.asarray(column, "float32")


class SGD:
    """reference ``v2/trainer.py`` SGD: cost + parameters + update rule."""

    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True):
        self.__metrics = dict(getattr(cost, "v2_metrics", {}))
        self.cost = cost
        self.parameters = parameters
        self.program = cost.block.program
        # evaluators declared through the legacy DSL ride the event
        # metrics (reference: trainer polls Evaluator objects each batch)
        from paddle_tpu.trainer_config_helpers.evaluators import \
            evaluators_of
        for ev_name, outs in evaluators_of(self.program).items():
            for k, v in outs.items():
                self.__metrics.setdefault(f"{ev_name}.{k}", v)
        self.test_program = self.program.clone(for_test=True)
        with fluid.program_guard(self.program,
                                 parameters._startup):
            self.optimizer = update_equation.to_fluid()
            self.optimizer.minimize(cost)
        self.exe = fluid.Executor()

    def _feed(self, data_batch, feeding):
        block = self.program.global_block()
        if feeding is None:
            # column order = declaration order of data vars
            names = [v.name for v in block.vars.values()
                     if getattr(v, "is_data", False)]
            feeding = {n: i for i, n in enumerate(names)}
        feed = {}
        for name, col in feeding.items():
            var = block.var(name)
            column = [row[col] for row in data_batch]
            feed[name] = _feed_converter(var, column)
        return feed

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        if event_handler is None:
            event_handler = lambda e: None
        self.parameters._init_once(self.exe)
        fetches = [self.cost.name] + list(self.__metrics.values())
        metric_names = list(self.__metrics)
        with fluid.scope_guard(self.parameters._scope):
            for pass_id in range(num_passes):
                event_handler(v2_event.BeginPass(pass_id))
                metrics = {}
                for batch_id, data_batch in enumerate(reader()):
                    event_handler(
                        v2_event.BeginIteration(pass_id, batch_id))
                    res = self.exe.run(
                        self.program,
                        feed=self._feed(data_batch, feeding),
                        fetch_list=fetches)
                    cost = float(np.asarray(res[0]).reshape(()))
                    metrics = {n: _metric_value(v)
                               for n, v in zip(metric_names, res[1:])}
                    event_handler(v2_event.EndIteration(
                        pass_id, batch_id, cost, metrics))
                event_handler(v2_event.EndPass(pass_id, metrics))

    def test(self, reader, feeding=None):
        self.parameters._init_once(self.exe)
        fetches = [self.cost.name] + list(self.__metrics.values())
        metric_names = list(self.__metrics)
        costs, counts = [], 0
        metrics_sum = {n: 0.0 for n in metric_names}
        with fluid.scope_guard(self.parameters._scope):
            for data_batch in reader():
                res = self.exe.run(self.test_program,
                                   feed=self._feed(data_batch, feeding),
                                   fetch_list=fetches)
                costs.append(float(np.asarray(res[0]).reshape(())))
                for n, v in zip(metric_names, res[1:]):
                    metrics_sum[n] = metrics_sum[n] + _metric_value(v)
                counts += 1
        metrics = {n: s / max(counts, 1) for n, s in metrics_sum.items()}
        return v2_event.TestResult(float(np.mean(costs)), metrics)
