"""v2 input-type declarations (reference
``python/paddle/v2/data_type.py`` / ``trainer/PyDataProvider2.py``)."""

from __future__ import annotations


class SequenceType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataType:
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class InputType:
    def __init__(self, dim, seq_type, data_type):
        self.dim = dim
        self.seq_type = seq_type
        self.type = data_type


def dense_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_array(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_vector_sequence(dim):
    return dense_vector(dim, SequenceType.SEQUENCE)


def integer_value(value_range, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def integer_value_sequence(value_range):
    return integer_value(value_range, SequenceType.SEQUENCE)


def sparse_binary_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_float_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)
