"""v2 optimizers -> fluid graph-op optimizers (reference
``python/paddle/v2/optimizer.py`` wrapped SWIG ParameterUpdater; here a
thin factory)."""

from __future__ import annotations

import paddle_tpu.optimizer as fopt

__all__ = ["Momentum", "Adam", "Adamax", "AdaGrad", "DecayedAdaGrad",
           "AdaDelta", "RMSProp", "Optimizer"]


class Optimizer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def to_fluid(self):
        raise NotImplementedError


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, learning_rate=1e-3, sparse=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.learning_rate = learning_rate

    def to_fluid(self):
        return fopt.Momentum(learning_rate=self.learning_rate,
                             momentum=self.momentum)


class Adam(Optimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon)

    def to_fluid(self):
        return fopt.Adam(**self.args)


class Adamax(Optimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2)

    def to_fluid(self):
        return fopt.Adamax(**self.args)


class AdaGrad(Optimizer):
    def __init__(self, learning_rate=1e-3, epsilon=1e-6, **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, epsilon=epsilon)

    def to_fluid(self):
        return fopt.Adagrad(**self.args)


class DecayedAdaGrad(Optimizer):
    def __init__(self, learning_rate=1e-3, rho=0.95, epsilon=1e-6,
                 **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, decay=rho,
                         epsilon=epsilon)

    def to_fluid(self):
        return fopt.DecayedAdagrad(**self.args)


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, learning_rate=1.0, **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, rho=rho,
                         epsilon=epsilon)

    def to_fluid(self):
        return fopt.Adadelta(**self.args)


class RMSProp(Optimizer):
    def __init__(self, learning_rate=1e-3, rho=0.95, epsilon=1e-6,
                 **kwargs):
        super().__init__(**kwargs)
        self.args = dict(learning_rate=learning_rate, rho=rho,
                         epsilon=epsilon)

    def to_fluid(self):
        return fopt.RMSProp(**self.args)
