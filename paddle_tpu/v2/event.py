"""v2 training events (reference ``python/paddle/v2/event.py:31-101``)."""

__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "TestResult"]


class WithMetric:
    def __init__(self, evaluator_metrics=None):
        self.metrics = dict(evaluator_metrics or {})


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator_metrics=None):
        super().__init__(evaluator_metrics)
        self.pass_id = pass_id


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator_metrics=None):
        super().__init__(evaluator_metrics)
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost


class TestResult(WithMetric):
    def __init__(self, cost, evaluator_metrics=None):
        super().__init__(evaluator_metrics)
        self.cost = cost
