"""Ploter: live training curves from the v2 trainer's event stream.

Reference ``python/paddle/v2/plot/plot.py:1-82``.  Typical use inside an
event handler::

    ploter = Ploter("train_cost", "test_cost")

    def handler(event):
        if isinstance(event, paddle.event.EndIteration):
            ploter.append("train_cost", event.batch_id, event.cost)
            ploter.plot()

Plotting is skipped entirely (appends still accumulate) when matplotlib
is unavailable or ``DISABLE_PLOT=True`` is set — so headless test runs
and notebook demos share one code path.
"""

from __future__ import annotations

import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    """One named curve: parallel lists of steps and values."""

    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(float(value))

    def reset(self):
        self.step = []
        self.value = []


def _load_pyplot():
    if os.environ.get("DISABLE_PLOT") == "True":
        return None
    try:
        import matplotlib
        if not os.environ.get("DISPLAY"):
            matplotlib.use("Agg")  # headless boxes
        import matplotlib.pyplot as plt
        return plt
    except Exception:
        return None


class Ploter:
    """Multi-curve live plot keyed by title; degrades to a no-op sink
    when plotting is disabled."""

    def __init__(self, *titles):
        self._titles = titles
        self._curves = {t: PlotData() for t in titles}
        self._plt = _load_pyplot()

    @property
    def curves(self):
        return self._curves

    def append(self, title, step, value):
        self._curves[title].append(step, value)

    def plot(self, path=None):
        """Redraw all non-empty curves; save to ``path`` when given,
        else display in place (IPython when available)."""
        if self._plt is None:
            return
        drawn = []
        for title in self._titles:
            curve = self._curves[title]
            if curve.step:
                self._plt.plot(curve.step, curve.value)
                drawn.append(title)
        if drawn:
            self._plt.legend(drawn, loc="upper left")
        if path is not None:
            self._plt.savefig(path)
        else:
            try:
                from IPython import display
                display.clear_output(wait=True)
                display.display(self._plt.gcf())
            except Exception:
                self._plt.draw()
        self._plt.gcf().clear()

    def reset(self):
        for curve in self._curves.values():
            curve.reset()
