"""Training-curve plotting over the v2 event stream (reference
``python/paddle/v2/plot/plot.py:1-82``)."""

from paddle_tpu.v2.plot.plot import Ploter, PlotData

__all__ = ["Ploter", "PlotData"]
