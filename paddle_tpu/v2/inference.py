"""v2 inference (reference ``python/paddle/v2/inference.py`` infer())."""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.v2.trainer import _feed_converter

__all__ = ["infer", "Inference"]


class Inference:
    def __init__(self, output_layer, parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self.outputs = outputs
        self.parameters = parameters
        program = outputs[0].block.program
        self.program = program.clone(for_test=True).prune(
            [o.name for o in outputs])

    def infer(self, input, feeding=None, field="value"):
        exe = fluid.Executor()
        self.parameters._init_once(exe)
        block = self.program.global_block()
        if feeding is None:
            names = [v.name for v in block.vars.values()
                     if getattr(v, "is_data", False)]
            feeding = {n: i for i, n in enumerate(names)}
        feed = {}
        for name, col in feeding.items():
            if not block.has_var(name):
                continue
            var = block.var(name)
            column = [row[col] for row in input]
            feed[name] = _feed_converter(var, column)
        with fluid.scope_guard(self.parameters._scope):
            res = exe.run(self.program, feed=feed,
                          fetch_list=[o.name for o in self.outputs])
        return res[0] if len(res) == 1 else res


def infer(output_layer, parameters, input, feeding=None, field="value"):
    return Inference(output_layer, parameters).infer(input, feeding=feeding,
                                                     field=field)
