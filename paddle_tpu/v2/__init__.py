"""paddle.v2-compatible API shim (reference ``python/paddle/v2/``).

The legacy v2 stack (layer DSL -> config_parser -> protobuf ModelConfig ->
SWIG GradientMachine, W1-W4 + V1-V14 in SURVEY.md) is SUBSUMED here by a
thin adapter: every v2 layer call builds the same Program IR the fluid
path uses, and ``trainer.SGD`` drives the XLA Executor.  The >130k LoC of
legacy C++ (gserver layers, math::Matrix, hl_* CUDA, trainer, pserver)
has no separate TPU equivalent — one IR, one compiler.

Usage (mirrors reference ``python/paddle/v2/__init__.py`` + README)::

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data(name='pixel',
                               type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(name='label',
                              type=paddle.data_type.integer_value(10))
    ...
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(momentum=0.9)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)
    trainer.train(reader=paddle.batch(paddle.dataset.mnist.train(), 128),
                  num_passes=5, event_handler=handler)
"""

from paddle_tpu.v2 import data_type
from paddle_tpu.v2 import activation
from paddle_tpu.v2 import attr
from paddle_tpu.v2 import layer
from paddle_tpu.v2 import networks
from paddle_tpu.v2 import optimizer
from paddle_tpu.v2 import parameters
from paddle_tpu.v2 import trainer
from paddle_tpu.v2 import event
from paddle_tpu.v2 import plot
from paddle_tpu.v2.minibatch import batch
from paddle_tpu.v2.inference import infer
from paddle_tpu import dataset
from paddle_tpu import reader

__all__ = ["init", "layer", "networks", "optimizer", "parameters",
           "trainer", "event", "batch", "infer", "dataset", "reader",
           "data_type", "activation", "attr", "plot"]

_initialized = False


def init(**kwargs):
    """Process init (reference ``v2/__init__.py`` init -> swig init;
    device selection is implicit on TPU)."""
    global _initialized
    _initialized = True
    return None
