"""v2 parameter/extra attributes (reference ``python/paddle/v2/attr.py``)."""

from paddle_tpu.param_attr import ParamAttr as _ParamAttr


class ParamAttr(_ParamAttr):
    def __init__(self, name=None, initial_std=None, initial_mean=None,
                 learning_rate=1.0, l2_rate=None, sparse_update=False,
                 initial_max=None, initial_min=None, **kwargs):
        from paddle_tpu import initializer, regularizer
        init = None
        if initial_std is not None or initial_mean is not None:
            init = initializer.Normal(loc=initial_mean or 0.0,
                                      scale=initial_std or 1.0)
        elif initial_max is not None or initial_min is not None:
            init = initializer.Uniform(low=initial_min or -1.0,
                                       high=initial_max or 1.0)
        reg = regularizer.L2Decay(l2_rate) if l2_rate else None
        super().__init__(name=name, initializer=init,
                         learning_rate=learning_rate, regularizer=reg)


Param = ParamAttr


class ExtraAttr:
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ExtraLayerAttribute = ExtraAttr
Extra = ExtraAttr
