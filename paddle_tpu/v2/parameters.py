"""v2 Parameters: name->numpy view over the scope (reference
``python/paddle/v2/parameters.py`` — there a dict over the SWIG
GradientMachine's parameter blobs; here over the Executor scope)."""

from __future__ import annotations

import io
import tarfile

import numpy as np

import paddle_tpu as fluid

__all__ = ["create", "Parameters"]


class Parameters:
    def __init__(self, program, startup):
        self._program = program
        self._startup = startup
        self._scope = fluid.Scope()
        self._initialized = False

    # -- lifecycle ---------------------------------------------------------
    def _init_once(self, exe=None):
        if self._initialized:
            return
        exe = exe or fluid.Executor()
        with fluid.scope_guard(self._scope):
            exe.run(self._startup)
        self._initialized = True

    # -- dict-like ---------------------------------------------------------
    def names(self):
        return [p.name for p in
                self._program.global_block().all_parameters()]

    def keys(self):
        return self.names()

    def has_key(self, name):
        return name in self.names()

    def __iter__(self):
        return iter(self.names())

    def get(self, name):
        self._init_once()
        v = self._scope.find_var(name)
        if v is None:
            raise KeyError(name)
        return np.asarray(v)

    __getitem__ = get

    def set(self, name, value):
        self._init_once()
        self._scope.set_var(name, np.asarray(value))

    __setitem__ = set

    def get_shape(self, name):
        return tuple(self._program.global_block().var(name).shape)

    # -- serialization (reference to_tar/from_tar) -------------------------
    def to_tar(self, f):
        self._init_once()
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.names():
                buf = io.BytesIO()
                np.save(buf, self.get(name))
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    @staticmethod
    def from_tar(f):
        """Returns a plain dict name->ndarray; pass to ``init_from``."""
        out = {}
        with tarfile.open(fileobj=f, mode="r") as tar:
            for m in tar.getmembers():
                out[m.name] = np.load(
                    io.BytesIO(tar.extractfile(m).read()))
        return out

    def init_from_tar(self, f):
        for name, arr in Parameters.from_tar(f).items():
            if self.has_key(name):
                self.set(name, arr)


def create(cost):
    """Build Parameters for the model that produces ``cost``
    (reference ``parameters.py`` create -> from proto)."""
    program = cost.block.program
    startup = fluid.default_startup_program()
    return Parameters(program, startup)
