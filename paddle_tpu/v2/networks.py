"""v2 composite networks (reference ``python/paddle/v2/networks.py`` ->
``trainer_config_helpers/networks.py``)."""

from __future__ import annotations

import paddle_tpu.nets as nets
import paddle_tpu.layers as F
from paddle_tpu.v2 import layer as v2_layer

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "simple_lstm", "simple_gru", "bidirectional_lstm"]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    return nets.simple_img_conv_pool(
        input=input, filter_size=filter_size, num_filters=num_filters,
        pool_size=pool_size, pool_stride=pool_stride,
        act=v2_layer._act_name(act))


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=1, pool_type="max", **kwargs):
    return nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=v2_layer._act_name(conv_act),
        conv_with_batchnorm=conv_with_batchnorm,
        conv_batchnorm_drop_rate=conv_batchnorm_drop_rate,
        pool_stride=pool_stride, pool_type=pool_type)


def sequence_conv_pool(input, context_len, hidden_size, pool_type="max",
                       act=None, **kwargs):
    return nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len,
        act=v2_layer._act_name(act) or "tanh", pool_type=pool_type)


def simple_lstm(input, size, **kwargs):
    proj = F.fc(input=input, size=size * 4)
    hidden, _ = F.dynamic_lstm(input=proj, size=size * 4)
    return hidden


def simple_gru(input, size, **kwargs):
    proj = F.fc(input=input, size=size * 3)
    return F.dynamic_gru(input=proj, size=size)


def bidirectional_lstm(input, size, return_concat=True, **kwargs):
    fwd = simple_lstm(input, size)
    proj = F.fc(input=input, size=size * 4)
    bwd, _ = F.dynamic_lstm(input=proj, size=size * 4, is_reverse=True)
    if return_concat:
        return F.concat(input=[fwd, bwd], axis=1)
    return fwd, bwd
