"""v2 layer DSL -> Program IR (reference ``python/paddle/v2/layer.py`` +
``trainer_config_helpers/layers.py``; here each call appends ops to the
default fluid-style programs instead of emitting ModelConfig protobuf)."""

from __future__ import annotations

import numpy as np

import paddle_tpu.layers as F
from paddle_tpu import nets
from paddle_tpu.v2 import data_type as dt
from paddle_tpu.v2.activation import BaseActivation

__all__ = [
    "data", "fc", "embedding", "img_conv", "img_pool", "batch_norm",
    "dropout", "concat", "lstmemory", "gru", "pooling", "last_seq",
    "first_seq", "classification_cost", "cross_entropy_cost",
    "square_error_cost", "mse_cost", "regression_cost",
    "crf", "crf_decoding", "max_id", "rank_cost", "huber_cost",
    "seq_concat", "expand", "scaling", "slope_intercept",
    "pooling_types",
]


class pooling_types:  # namespace parity (v2.pooling.Max etc. below)
    pass


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, type) and issubclass(act, BaseActivation):
        return act.name
    if isinstance(act, BaseActivation):
        return act.name
    return act


def data(name, type, height=None, width=None):
    """Declare an input (reference ``v2/layer.py`` data_layer)."""
    if type.type == dt.DataType.Index:
        v = F.data(name=name, shape=[1], dtype="int64",
                   lod_level=1 if type.seq_type else 0)
    else:
        lod = 1 if type.seq_type else 0
        v = F.data(name=name, shape=[type.dim], dtype="float32",
                   lod_level=lod)
    v.v2_input_type = type
    return v


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return F.fc(input=list(ins), size=size, act=_act_name(act),
                param_attr=param_attr, bias_attr=bias_attr, name=name)


def embedding(input, size, param_attr=None):
    return F.embedding(input=input, size=[_vocab_of(input), size],
                       param_attr=param_attr)


def _vocab_of(var):
    t = getattr(var, "v2_input_type", None)
    if t is None:
        raise ValueError("embedding input must be a v2 data layer of "
                         "integer_value type")
    return t.dim


def img_conv(input, filter_size, num_filters, num_channel=None, act=None,
             padding=0, stride=1, bias_attr=None, param_attr=None,
             name=None):
    return F.conv2d(input=input, num_filters=num_filters,
                    filter_size=filter_size, stride=stride,
                    padding=padding, act=_act_name(act),
                    bias_attr=bias_attr, param_attr=param_attr, name=name)


def img_pool(input, pool_size, pool_type=None, stride=None, padding=0,
             name=None):
    ptype = getattr(pool_type, "name", pool_type) or "max"
    return F.pool2d(input=input, pool_size=pool_size, pool_type=ptype,
                    pool_stride=stride or pool_size,
                    pool_padding=padding, name=name)


def batch_norm(input, act=None, **kwargs):
    return F.batch_norm(input=input, act=_act_name(act))


def dropout(input, dropout_rate):
    return F.dropout(input, dropout_prob=dropout_rate)


def concat(input, name=None):
    return F.concat(input=list(input), axis=1)


def lstmemory(input, size=None, reverse=False, act=None,
              gate_act=None, state_act=None, param_attr=None,
              bias_attr=None, name=None):
    """v2 lstmemory: input must be 4*size wide (pre-projected), like the
    reference (``trainer_config_helpers/layers.py`` lstmemory)."""
    size = size or input.shape[-1] // 4
    hidden, _ = F.dynamic_lstm(
        input=input, size=4 * size, is_reverse=reverse,
        use_peepholes=True,
        gate_activation=_act_name(gate_act) or "sigmoid",
        cell_activation=_act_name(state_act) or "tanh",
        candidate_activation=_act_name(act) or "tanh",
        param_attr=param_attr, bias_attr=bias_attr)
    return hidden


def gru(input, size=None, reverse=False, act=None, gate_act=None, **kwargs):
    if size is None:
        size = input.shape[-1] // 3  # reference DSL infers from [N, 3H]
    return F.dynamic_gru(
        input=input, size=size, is_reverse=reverse,
        gate_activation=_act_name(gate_act) or "sigmoid",
        candidate_activation=_act_name(act) or "tanh")


grumemory = gru


class _PoolType:
    def __init__(self, name):
        self.name = name


class Max(_PoolType):
    def __init__(self):
        super().__init__("max")


class Avg(_PoolType):
    def __init__(self):
        super().__init__("average")


class Sum(_PoolType):
    def __init__(self):
        super().__init__("sum")


def pooling(input, pooling_type=None, name=None):
    ptype = pooling_type.name if pooling_type else "max"
    return F.sequence_pool(input=input, pool_type=ptype)


def last_seq(input, name=None):
    return F.sequence_last_step(input)


def first_seq(input, name=None):
    return F.sequence_first_step(input)


def classification_cost(input, label, name=None):
    """input carries softmax output (v2 convention); adds cross-entropy +
    tracks accuracy for the trainer's event metrics."""
    cost = F.cross_entropy(input=input, label=label)
    avg = F.mean(cost)
    avg.v2_metrics = {
        "classification_error_evaluator": _one_minus_accuracy(input, label)}
    return avg


def _one_minus_accuracy(input, label):
    acc = F.accuracy(input=input, label=label)
    return F.scale(acc, scale=-1.0, bias=1.0)


def cross_entropy_cost(input, label, name=None):
    return F.mean(F.cross_entropy(input=input, label=label))


def square_error_cost(input, label, name=None):
    return F.mean(F.square_error_cost(input=input, label=label))


mse_cost = square_error_cost
regression_cost = square_error_cost


# --- additional legacy layer types (gserver/layers parity subset) --------

def crf(input, label, size=None, param_attr=None, name=None):
    """CRF cost layer (reference v2 crf_layer over CRFLayer.cpp); like
    every v2 cost layer, returns the scalar mean cost."""
    from paddle_tpu.param_attr import ParamAttr as _PA
    return F.mean(F.linear_chain_crf(input=input, label=label,
                                     param_attr=_PA.to_attr(param_attr)))


def crf_decoding(input, size=None, label=None, param_attr=None, name=None):
    """CRF viterbi decode layer (reference v2 crf_decoding_layer)."""
    from paddle_tpu.param_attr import ParamAttr as _PA
    return F.crf_decoding(input=input, param_attr=_PA.to_attr(param_attr),
                          label=label)


def max_id(input, name=None):
    """Argmax over the last axis (reference v2 maxid_layer)."""
    return F.argmax(input, axis=-1)


def rank_cost(left, right, label, name=None):
    """Pairwise rank cost (reference v2 rank_cost over rank_loss_op)."""
    return F.mean(F.rank_loss(left, right, label, name=name))


def huber_cost(input, label, delta=1.0, name=None):
    """Huber regression cost (reference v2 huber_cost over huber_loss_op)."""
    return F.mean(F.huber_loss(input, label, delta=delta, name=name))


def seq_concat(a, b, name=None):
    """Per-sequence concatenation (reference v2 seq_concat_layer)."""
    return F.sequence_concat(input=[a, b])


def expand(input, expand_as, name=None):
    """Repeat rows to match another sequence's lod (reference v2
    expand_layer over sequence_expand)."""
    return F.sequence_expand(x=input, y=expand_as)


def scaling(input, weight, name=None):
    """Per-row scaling (reference v2 scaling_layer)."""
    return F.elementwise_mul(input, weight, axis=0)


def slope_intercept(input, slope=1.0, intercept=0.0, name=None):
    """y = slope*x + intercept (reference v2 slope_intercept_layer)."""
    return F.scale(input, scale=slope, bias=intercept)


# ---------------------------------------------------------------------------
# legacy-DSL aliasing: the reference v2/layer.py generates its layer
# namespace from trainer_config_helpers (``v2/layer.py:__convert_to_v2__``);
# here a lazy module __getattr__ resolves ``v2.layer.foo`` to the legacy
# ``foo`` / ``foo_layer`` implementation, avoiding a circular import.
# ---------------------------------------------------------------------------

def __getattr__(name):
    if name.startswith("_"):
        raise AttributeError(name)
    import paddle_tpu.trainer_config_helpers.layers as _tch
    for cand in (name, name + "_layer"):
        if hasattr(_tch, cand):
            obj = getattr(_tch, cand)
            globals()[name] = obj
            return obj
    raise AttributeError(name)
