"""Image preprocessing utilities (reference ``python/paddle/v2/image.py``,
which uses cv2; re-implemented over PIL + numpy — same function surface:
resize_short, to_chw, center_crop, random_crop, left_right_flip,
simple_transform, load_and_transform, batch_images)."""

from __future__ import annotations

import numpy as np

__all__ = [
    "load_image", "resize_short", "to_chw", "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
    "batch_images",
]


def load_image(file_path, is_color=True):
    """Load an image file to an HWC uint8 array (reference load_image)."""
    from PIL import Image
    img = Image.open(file_path)
    img = img.convert("RGB" if is_color else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if not is_color:
        arr = arr[:, :, None]
    return arr


def resize_short(im, size):
    """Resize so the SHORT side equals ``size``, keeping aspect ratio
    (reference resize_short)."""
    from PIL import Image
    h, w = im.shape[0], im.shape[1]
    if h > w:
        new_w, new_h = size, int(round(h * size / w))
    else:
        new_w, new_h = int(round(w * size / h)), size
    squeeze = im.shape[2] == 1
    pil = Image.fromarray(im[:, :, 0] if squeeze else im)
    pil = pil.resize((new_w, new_h), Image.BILINEAR)
    out = np.asarray(pil)
    if squeeze:
        out = out[:, :, None]
    return out


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference to_chw)."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[0], im.shape[1]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[0], im.shape[1]
    h_start = rng.randint(0, h - size + 1)
    w_start = rng.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im):
    return im[:, ::-1, :]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> crop (random+flip when training, center otherwise)
    -> CHW float32, optionally mean-subtracted (reference
    simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        rng_ = rng or np.random
        if rng_.randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype("float32")
    if mean is not None:
        mean = np.asarray(mean, dtype="float32")
        if mean.ndim == 1:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images(img_reader, batch_size):
    """Group an image reader into stacked [N, C, H, W] batches."""
    def reader():
        batch = []
        for im in img_reader():
            batch.append(im)
            if len(batch) == batch_size:
                yield np.stack(batch)
                batch = []
        if batch:
            yield np.stack(batch)

    return reader
