"""PyDataProvider2: the v2 ``@provider`` data protocol.

Reference: ``python/paddle/trainer/PyDataProvider2.py:365`` — a decorated
generator yields samples whose slots are declared by ``input_types``; the
legacy C++ DataProvider (``gserver/dataproviders/PyDataProvider2.cpp``)
embedded CPython to drain it.  Here the decorated provider converts
directly into a plain reader (``paddle_tpu.reader`` composes the rest).

The input-type declarations are the SAME objects as
``paddle_tpu.v2.data_type`` (one definition, re-exported), so types built
through either module work with ``@provider``.
"""

from __future__ import annotations

import functools

import numpy as np

from paddle_tpu.v2.data_type import (  # noqa: F401  (re-exports)
    SequenceType, DataType, InputType, dense_vector, dense_vector_sequence,
    sparse_binary_vector, sparse_float_vector, integer_value,
    integer_value_sequence)

__all__ = [
    "provider", "dense_vector", "dense_vector_sequence",
    "sparse_binary_vector", "sparse_binary_vector_sequence",
    "sparse_float_vector", "sparse_float_vector_sequence", "integer_value",
    "integer_value_sequence", "SequenceType", "DataType", "CacheType",
    "InputType", "convert_slot",
]


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SequenceType.SEQUENCE)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, SequenceType.SEQUENCE)


def convert_slot(input_type, value, validate=False):
    """Convert one slot value to numpy per its InputType declaration
    (dense realization: sparse slots become dense vectors — the TPU
    build's sparse path begins at the embedding layer, not the feed).
    ``validate`` adds the reference's range/shape checking."""
    t = input_type
    if t.type == DataType.Index:
        if t.seq_type == SequenceType.NO_SEQUENCE:
            v = int(value)
            if validate and not 0 <= v < t.dim:
                raise ValueError(f"index {v} out of range [0, {t.dim})")
            return np.asarray([v], dtype="int64")
        arr = np.asarray(value, dtype="int64").reshape(-1, 1)
        if validate and arr.size and not (
                (arr >= 0) & (arr < t.dim)).all():
            raise ValueError(f"index sequence out of range [0, {t.dim})")
        return arr
    if t.type == DataType.Dense:
        arr = np.asarray(value, dtype="float32")
        if validate and arr.shape[-1] != t.dim:
            raise ValueError(
                f"dense slot expects dim {t.dim}, got {arr.shape}")
        return arr

    def densify(ids):
        out = np.zeros(t.dim, dtype="float32")
        if t.type == DataType.SparseNonValue:
            out[np.asarray(ids, dtype="int64")] = 1.0
        else:
            for i, v in ids:
                out[int(i)] = float(v)
        return out

    if t.seq_type == SequenceType.NO_SEQUENCE:
        return densify(value)
    return np.stack([densify(v) for v in value])


class DataProvider:
    """The decorated provider object: iterate files to samples, or turn
    into a plain reader for ``paddle.batch``/``trainer.train``."""

    def __init__(self, generator, input_types, init_hook=None,
                 cache=CacheType.NO_CACHE, should_shuffle=None,
                 check=False, **kwargs):
        self.generator = generator
        self.input_types = input_types
        self.init_hook = init_hook
        self.cache = cache
        self.check = check
        self.kwargs = kwargs
        self._cache_store = {}   # filenames tuple -> drained samples
        functools.update_wrapper(self, generator)

    def _ordered_types(self):
        if isinstance(self.input_types, dict):
            return list(self.input_types.items())
        return [(i, t) for i, t in enumerate(self.input_types)]

    def _convert(self, sample):
        items = self._ordered_types()
        if isinstance(sample, dict):
            values = [sample[k] for k, _ in items]
        elif isinstance(sample, (list, tuple)) and len(items) > 1:
            values = list(sample)
        else:
            values = [sample]
        if len(values) != len(items):
            raise ValueError(
                f"provider yielded {len(values)} slots, expected "
                f"{len(items)}")
        return tuple(convert_slot(t, v, validate=self.check)
                     for (_, t), v in zip(items, values))

    def __call__(self, obj=None, filename=None):
        """Drain one file (reference protocol: process(settings, filename));
        returns a generator of converted samples."""

        class _Settings:
            pass

        settings = _Settings()
        settings.input_types = self.input_types
        if self.init_hook is not None:
            self.init_hook(settings, filename=filename, **self.kwargs)
        for sample in self.generator(settings, filename):
            yield self._convert(sample)

    def as_reader(self, filenames):
        """Plain reader over a list of files, honoring CACHE_PASS_IN_MEM
        (reference CacheType semantics: first pass reads, later passes
        serve from memory; cached per filenames tuple)."""
        if isinstance(filenames, str):
            filenames = [filenames]
        key = tuple(filenames)

        def reader():
            if self.cache == CacheType.CACHE_PASS_IN_MEM and \
                    key in self._cache_store:
                yield from self._cache_store[key]
                return
            store = [] if self.cache == CacheType.CACHE_PASS_IN_MEM else None
            for fn in filenames:
                for sample in self(None, fn):
                    if store is not None:
                        store.append(sample)
                    yield sample
            if store is not None:
                self._cache_store[key] = store

        return reader


def provider(input_types=None, should_shuffle=None, pool_size=-1,
             min_pool_size=-1, can_over_batch_size=True, calc_batch_size=None,
             cache=CacheType.NO_CACHE, check=False, check_fail_continue=False,
             init_hook=None, **kwargs):
    """The ``@provider`` decorator (reference ``PyDataProvider2.py:365``)."""
    if input_types is None:
        raise ValueError("provider requires input_types")

    def deco(fn):
        return DataProvider(fn, input_types, init_hook=init_hook,
                            cache=cache, should_shuffle=should_shuffle,
                            check=check, **kwargs)

    return deco
