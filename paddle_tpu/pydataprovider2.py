"""PyDataProvider2: the v2 ``@provider`` data protocol.

Reference: ``python/paddle/trainer/PyDataProvider2.py:365`` — a decorated
generator yields samples whose slots are declared by ``input_types``; the
legacy C++ DataProvider (``gserver/dataproviders/PyDataProvider2.cpp``)
embedded CPython to drain it.  Here the decorated provider converts
directly into a plain reader (``paddle_tpu.reader`` composes the rest),
with the same input-type declarations and per-slot value checking.
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = [
    "provider", "dense_vector", "dense_vector_sequence", "sparse_binary_vector",
    "sparse_binary_vector_sequence", "sparse_float_vector",
    "sparse_float_vector_sequence", "integer_value", "integer_value_sequence",
    "SequenceType", "DataType", "CacheType", "InputType",
]


class SequenceType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataType:
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


class InputType:
    """Declares one slot: dimension, sequence nesting, and data type
    (reference ``PyDataProvider2.py:63``)."""

    __slots__ = ("dim", "seq_type", "type")

    def __init__(self, dim, seq_type, tp):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp

    def __repr__(self):
        return (f"InputType(dim={self.dim}, seq_type={self.seq_type}, "
                f"type={self.type})")

    def convert(self, value):
        """Check + convert one slot value to numpy (dense realization:
        sparse slots become dense vectors — the TPU build's SelectedRows
        path begins at the embedding layer, not the feed)."""
        if self.type == DataType.Index:
            if self.seq_type == SequenceType.NO_SEQUENCE:
                v = int(value)
                if not 0 <= v < self.dim:
                    raise ValueError(
                        f"index {v} out of range [0, {self.dim})")
                return np.asarray([v], dtype="int64")
            return np.asarray(value, dtype="int64").reshape(-1, 1)
        if self.type == DataType.Dense:
            arr = np.asarray(value, dtype="float32")
            if arr.shape[-1] != self.dim:
                raise ValueError(
                    f"dense slot expects dim {self.dim}, got {arr.shape}")
            return arr
        # sparse slots: list of ids or (id, value) pairs -> dense vector
        def densify(ids):
            out = np.zeros(self.dim, dtype="float32")
            if self.type == DataType.SparseNonValue:
                out[np.asarray(ids, dtype="int64")] = 1.0
            else:
                for i, v in ids:
                    out[int(i)] = float(v)
            return out

        if self.seq_type == SequenceType.NO_SEQUENCE:
            return densify(value)
        return np.stack([densify(v) for v in value])


def dense_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def sparse_binary_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_float_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)


def integer_value(value_range, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def dense_vector_sequence(dim):
    return dense_vector(dim, SequenceType.SEQUENCE)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SequenceType.SEQUENCE)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, SequenceType.SEQUENCE)


def integer_value_sequence(value_range):
    return integer_value(value_range, SequenceType.SEQUENCE)


class DataProvider:
    """The decorated provider object: iterate files to samples, or turn
    into a plain reader for ``paddle.batch``/``trainer.train``."""

    def __init__(self, generator, input_types, init_hook=None,
                 cache=CacheType.NO_CACHE, should_shuffle=None,
                 check=False, **kwargs):
        self.generator = generator
        self.input_types = input_types
        self.init_hook = init_hook
        self.cache = cache
        self.check = check
        self.kwargs = kwargs
        self._cache_store = None
        functools.update_wrapper(self, generator)

    def _ordered_types(self):
        if isinstance(self.input_types, dict):
            return list(self.input_types.items())
        return [(i, t) for i, t in enumerate(self.input_types)]

    def _convert(self, sample):
        items = self._ordered_types()
        if isinstance(sample, dict):
            values = [sample[k] for k, _ in items]
        elif isinstance(sample, (list, tuple)) and len(items) > 1:
            values = list(sample)
        else:
            values = [sample]
        if len(values) != len(items):
            raise ValueError(
                f"provider yielded {len(values)} slots, expected "
                f"{len(items)}")
        if self.check:
            return tuple(t.convert(v) for (_, t), v in zip(items, values))
        return tuple(values)

    def __call__(self, obj=None, filename=None):
        """Drain one file (reference protocol: process(settings, filename));
        returns a generator of converted samples."""

        class _Settings:
            pass

        settings = _Settings()
        settings.input_types = self.input_types
        if self.init_hook is not None:
            self.init_hook(settings, filename=filename, **self.kwargs)
        for sample in self.generator(settings, filename):
            yield self._convert(sample)

    def as_reader(self, filenames):
        """Plain reader over a list of files, honoring CACHE_PASS_IN_MEM
        (reference CacheType semantics: first pass reads, later passes
        serve from memory)."""
        if isinstance(filenames, str):
            filenames = [filenames]

        def reader():
            if self.cache == CacheType.CACHE_PASS_IN_MEM and \
                    self._cache_store is not None:
                yield from self._cache_store
                return
            store = [] if self.cache == CacheType.CACHE_PASS_IN_MEM else None
            for fn in filenames:
                for sample in self(None, fn):
                    if store is not None:
                        store.append(sample)
                    yield sample
            if store is not None:
                self._cache_store = store

        return reader


def provider(input_types=None, should_shuffle=None, pool_size=-1,
             min_pool_size=-1, can_over_batch_size=True, calc_batch_size=None,
             cache=CacheType.NO_CACHE, check=False, check_fail_continue=False,
             init_hook=None, **kwargs):
    """The ``@provider`` decorator (reference ``PyDataProvider2.py:365``)."""
    if input_types is None:
        raise ValueError("provider requires input_types")

    def deco(fn):
        return DataProvider(fn, input_types, init_hook=init_hook,
                            cache=cache, should_shuffle=should_shuffle,
                            check=check, **kwargs)

    return deco
