"""Serving runtime: Predictor, HTTP inference server, and the C-ABI
helpers behind ``native/capi.cpp``.

Reference L6 surface: the C++ inference loader (``inference/io.h:35`` +
``inference/tests/book``) and the embeddable pure-C ABI
(``paddle/capi/capi.h`` ``paddle_gradient_machine_*``).  TPU re-design:
the compute runs through XLA/PJRT either way; the native shell
(``native/capi.cpp``) embeds CPython to drive this module — the mirror
image of the reference, which embedded CPython in its C++ data layer
(``PyDataProvider2.cpp``)."""

from __future__ import annotations

import json
import threading

import numpy as np

__all__ = ["Predictor", "serve", "InferenceServer"]


class Predictor:
    """Load-once, run-many inference handle over a saved inference model
    (the ``paddle_gradient_machine`` analog)."""

    def __init__(self, model_dir):
        import paddle_tpu as fluid

        self._fluid = fluid
        self._scope = fluid.Scope()
        self._lock = threading.Lock()  # Executor/scope are not re-entrant
        with fluid.scope_guard(self._scope):
            self._exe = fluid.Executor()
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                model_dir, self._exe)

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return [t.name if hasattr(t, "name") else str(t)
                for t in self._fetch_targets]

    def run(self, feed):
        """feed: dict name -> ndarray; returns list of ndarrays."""
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        with self._lock, self._fluid.scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=dict(feed),
                                 fetch_list=self._fetch_targets)
        return [np.asarray(o) for o in outs]


# ---------------------------------------------------------------------------
# C-ABI bridge helpers (called from native/capi.cpp via the CPython API)
# ---------------------------------------------------------------------------

def _capi_create(model_dir):
    return Predictor(model_dir)


def _capi_feed_names(predictor):
    return predictor.feed_names


def _capi_run(predictor, names, buffers, shapes, dtypes):
    """names: list[str]; buffers: list[memoryview of raw bytes];
    shapes: list[tuple]; dtypes: list[str].  Returns
    (list[bytes], list[tuple[int]], list[str]) for the outputs."""
    feed = {}
    for name, buf, shape, dtype in zip(names, buffers, shapes, dtypes):
        feed[name] = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    outs = predictor.run(feed)
    payloads = [np.ascontiguousarray(o).tobytes() for o in outs]
    out_shapes = [tuple(int(d) for d in o.shape) for o in outs]
    out_dtypes = [str(o.dtype) for o in outs]
    return payloads, out_shapes, out_dtypes


# ---------------------------------------------------------------------------
# HTTP inference server (the serving-runtime gap in L6; JSON in/out)
# ---------------------------------------------------------------------------

class InferenceServer:
    def __init__(self, model_dir, host="127.0.0.1", port=0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        predictor = Predictor(model_dir)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply(200, {"status": "ok"})
                elif self.path == "/meta":
                    self._reply(200, {"feeds": predictor.feed_names,
                                      "fetches": predictor.fetch_names})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    feed = {k: np.asarray(v, dtype="float32")
                            if not isinstance(v, dict)
                            else np.asarray(v["data"],
                                            dtype=v.get("dtype", "float32"))
                            for k, v in req["feeds"].items()}
                    outs = predictor.run(feed)
                    self._reply(200, {"outputs": [o.tolist() for o in outs],
                                      "shapes": [list(o.shape)
                                                 for o in outs]})
                except Exception as e:
                    self._reply(400, {"error": str(e)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._server.server_address
        self.predictor = predictor

    def serve_forever(self):
        self._server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


def serve(model_dir, host="127.0.0.1", port=8866):
    server = InferenceServer(model_dir, host, port)
    print(f"serving {model_dir} on {server.addr[0]}:{server.addr[1]}",
          flush=True)
    server.serve_forever()
