"""Serving runtime: Predictor, HTTP inference server, and the C-ABI
helpers behind ``native/capi.cpp``.

Reference L6 surface: the C++ inference loader (``inference/io.h:35`` +
``inference/tests/book``) and the embeddable pure-C ABI
(``paddle/capi/capi.h`` ``paddle_gradient_machine_*``).  TPU re-design:
the compute runs through XLA/PJRT either way; the native shell
(``native/capi.cpp``) embeds CPython to drive this module — the mirror
image of the reference, which embedded CPython in its C++ data layer
(``PyDataProvider2.cpp``)."""

from __future__ import annotations

import json
import logging
import threading
import time

import numpy as np

from paddle_tpu.obs import trace as _trace
from paddle_tpu.obs.trace import span as _span, record_span as _record_span

logger = logging.getLogger(__name__)

__all__ = ["Predictor", "serve", "InferenceServer", "MicroBatcher",
           "DeadlineExceeded", "QueueFull", "BatcherCrashed",
           "ServingClient", "ServingError"]


class DeadlineExceeded(RuntimeError):
    """A request timed out waiting for the predictor (queue saturation)."""


class QueueFull(RuntimeError):
    """The batcher's bounded request queue is full (load shedding — the
    caller gets a retryable 503 instead of queueing unboundedly)."""


class BatcherCrashed(RuntimeError):
    """The batcher thread died on an unexpected exception.  Every
    pending request fails with this (a retryable 503 at the HTTP layer)
    instead of hanging until its client timeout; the batcher restarts
    itself within a bounded budget."""


class ServingError(RuntimeError):
    """Structured server-side error; ``retryable`` mirrors the reply."""

    def __init__(self, etype, message, retryable=False):
        super().__init__(f"{etype}: {message}")
        self.etype = etype
        self.retryable = retryable


class _TransientServingError(ConnectionError):
    """A retryable (503/504) reply, surfaced as a transport-class error
    so RetryPolicy's default ``retryable`` set covers it."""


class Predictor:
    """Load-once, run-many inference handle over a saved inference model
    (the ``paddle_gradient_machine`` analog)."""

    def __init__(self, model_dir):
        import paddle_tpu as fluid

        self._fluid = fluid
        self._scope = fluid.Scope()
        self._lock = threading.Lock()  # Executor/scope are not re-entrant
        # None until a batched dispatch proves (True) or disproves
        # (False) that outputs track the row axis; False short-circuits
        # run_many straight to per-request dispatches
        self._row_scatter_ok = None
        with fluid.scope_guard(self._scope):
            self._exe = fluid.Executor()
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                model_dir, self._exe)

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return [t.name if hasattr(t, "name") else str(t)
                for t in self._fetch_targets]

    def run(self, feed, timeout=None):
        """feed: dict name -> ndarray; returns list of ndarrays.

        ``timeout``: max seconds to wait for the (serialized) executor —
        a saturated predictor raises :class:`DeadlineExceeded` instead of
        queueing the caller indefinitely."""
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        if not self._lock.acquire(timeout=-1 if timeout is None
                                  else timeout):
            raise DeadlineExceeded(
                f"predictor busy for more than {timeout}s")
        try:
            # fires INSIDE the lock: a delay action models device time
            # serialized per predictor (the one-device-per-replica cost
            # model the fleet bench leans on); an error action models a
            # dispatch failure
            from paddle_tpu.fault import chaos as _chaos
            _chaos.fire("serving.predict", feeds=len(feed))
            with self._fluid.scope_guard(self._scope):
                outs = self._exe.run(self._program, feed=dict(feed),
                                     fetch_list=self._fetch_targets)
        finally:
            self._lock.release()
        return [np.asarray(o) for o in outs]

    def run_many(self, feeds_list, timeout=None):
        """Run several per-request feed dicts as ONE padded, row-bucketed
        dispatch (the micro-batching hot path).

        All requests must be batch-compatible — same feed names, dtypes
        and trailing dims, with a shared leading (row) axis; see
        :func:`batch_key`.  Rows are concatenated, zero-padded up to a
        ``lod.row_bucket`` edge (so the jit-cache key is the bucket, not
        the exact total), dispatched once, and the outputs are scattered
        back by row ranges.  Outputs whose leading dim does not track the
        row axis (e.g. a batch-reduced scalar) cannot be scattered: the
        batch falls back to per-request runs (counted as
        ``serving.batch_fallbacks``).  Returns a list of per-request
        output lists."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.lod import row_bucket

        if self._row_scatter_ok is False:
            # this model's outputs were seen not to track the row axis:
            # skip the (wasted) batched attempt entirely
            return [self.run(f, timeout=timeout) for f in feeds_list]
        if len(feeds_list) == 1:
            key, _ = batch_key(feeds_list[0])
            if key is None:
                return [self.run(feeds_list[0], timeout=timeout)]
        rows = []
        for f in feeds_list:
            _, r = batch_key(f)
            if r is None:
                raise ValueError("run_many got a non-batchable request in "
                                 "a batch of size > 1")
            rows.append(r)
        total = sum(rows)
        bucket = row_bucket(total)
        names = sorted(feeds_list[0])
        feed = {}
        for name in names:
            parts = [np.asarray(f[name]) for f in feeds_list]
            cat = parts[0] if len(parts) == 1 else np.concatenate(parts, 0)
            if bucket > total:
                pad = np.zeros((bucket - total,) + cat.shape[1:], cat.dtype)
                cat = np.concatenate([cat, pad], 0)
            feed[name] = cat
        outs = self.run(feed, timeout=timeout)
        if any(o.ndim == 0 or o.shape[0] != bucket for o in outs):
            # row-misaligned outputs: correctness beats throughput —
            # and remember, so later batches skip the wasted attempt
            self._row_scatter_ok = False
            logger.warning(
                "model outputs do not track the batch row axis; "
                "micro-batching disabled for this predictor (requests "
                "dispatch individually)")
            _profiler.runtime_metrics.inc("serving.batch_fallbacks")
            return [self.run(f, timeout=timeout) for f in feeds_list]
        self._row_scatter_ok = True
        results, off = [], 0
        for r in rows:
            results.append([o[off:off + r] for o in outs])
            off += r
        return results

    def warmup(self, batch_sizes=(1,), bucket=True):
        """AOT-compile the model for each batch size before traffic
        arrives (`Executor.warmup` over the DECLARED feed shapes of the
        loaded inference program).  ``bucket=True`` rounds sizes through
        ``lod.row_bucket`` — the shapes BATCHED dispatches actually see;
        pass ``bucket=False`` on the serialized path, where requests run
        unpadded and only exact sizes match.  Feeds whose trailing dims
        are dynamic or that carry LoD cannot be synthesized — warmup
        then skips (logged + ``warmup.skipped`` counter) and returns 0.
        Returns the number of fresh compiles."""
        from paddle_tpu import io as _io
        from paddle_tpu.lod import row_bucket

        from paddle_tpu import profiler as _profiler
        specs = _io.infer_feed_specs(self._program, self._feed_names)
        shapes = {}
        for name, spec in specs.items():
            shape = spec["shape"]
            if shape is None or spec["lod_level"] or len(shape) == 0 or \
                    any(d is None for d in shape[1:]):
                # can't synthesize this feed — say so loudly: /readyz
                # will flip with NOTHING compiled, and the first real
                # request pays the compile warmup exists to avoid
                logger.warning(
                    "warmup skipped: feed %r has dynamic non-batch dims "
                    "or LoD (%r) — no signature can be synthesized",
                    name, shape)
                _profiler.runtime_metrics.inc("warmup.skipped")
                return 0
            shapes[name] = shape
        sigs, seen = [], set()
        sizes = {row_bucket(b) if bucket else max(int(b), 1)
                 for b in batch_sizes}
        for b in sorted(sizes):
            sig = {name: tuple(shape) if shape[0] is not None
                   else (b,) + tuple(shape[1:])
                   for name, shape in shapes.items()}
            frozen = tuple(sorted((n, s) for n, s in sig.items()))
            if frozen not in seen:
                seen.add(frozen)
                sigs.append(sig)
        with self._lock:
            with self._fluid.scope_guard(self._scope):
                return self._exe.warmup(self._program, sigs,
                                        fetch_list=self._fetch_targets,
                                        scope=self._scope)


def batch_key(feed):
    """(compatibility key, rows) for a request feed — requests sharing a
    key can ride one padded dispatch (same feed names/dtypes/trailing
    dims form one stable jit-cache bucket).  ``(None, None)`` marks a
    non-batchable request: a rank-0 feed, or feeds that disagree on the
    leading (row) dim."""
    rows = None
    parts = []
    for name in sorted(feed):
        a = np.asarray(feed[name])
        if a.ndim == 0:
            return None, None
        if rows is None:
            rows = int(a.shape[0])
        elif int(a.shape[0]) != rows:
            return None, None
        parts.append((name, str(a.dtype), tuple(a.shape[1:])))
    if rows is None or rows == 0:
        return None, None
    return tuple(parts), rows


class _Pending:
    """One enqueued request awaiting its batch slot."""

    __slots__ = ("feed", "key", "rows", "event", "result", "error",
                 "abandoned", "enqueue_t", "trace_id")

    def __init__(self, feed, key, rows):
        self.feed = feed
        self.key = key
        self.rows = rows
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.abandoned = False
        # queue-wait measurement + cross-thread trace stitching: the
        # batcher thread records this request's spans under the trace id
        # the submitting handler was serving (the X-Request-Id)
        self.enqueue_t = time.perf_counter()
        self.trace_id = _trace.current_trace_id()


class MicroBatcher:
    """Dynamic request micro-batching over a :class:`Predictor`.

    Concurrent ``submit`` calls land in a bounded queue; a single batcher
    thread coalesces batch-compatible requests — up to ``max_batch_size``
    requests / ``max_batch_rows`` total rows, waiting at most
    ``max_batch_delay`` seconds after the first — into ONE padded
    dispatch through ``Predictor.run_many``, and scatters per-request
    outputs back.  Mixed-shape requests (different trailing dims or feed
    sets) never share a batch: each compatibility key is its own bucket.

    Degradation semantics mirror the serialized path: a full queue raises
    :class:`QueueFull` (503 load shedding), a request whose result does
    not arrive within its timeout raises :class:`DeadlineExceeded` (504)
    and its queue slot is abandoned.

    An UNEXPECTED exception escaping the batcher thread (a bug, not a
    per-batch dispatch failure — those already route to their batch)
    must not leave queued requests hanging until client timeout: every
    pending request fails immediately with :class:`BatcherCrashed`
    (503, retryable) and the thread restarts, up to ``max_restarts``
    times (``serving.batcher_restarts`` counts them); past the budget
    the batcher is dead and ``submit`` fails fast."""

    def __init__(self, predictor, max_batch_size=8, max_batch_delay=0.005,
                 queue_size=128, max_batch_rows=None, max_restarts=5):
        from paddle_tpu.lod import row_bucket
        self._predictor = predictor
        self.max_batch_size = max(1, int(max_batch_size))
        self.max_batch_delay = max(0.0, float(max_batch_delay))
        self.queue_size = max(1, int(queue_size))
        self.max_batch_rows = int(max_batch_rows) if max_batch_rows \
            else max(row_bucket(self.max_batch_size), self.max_batch_size)
        self.max_restarts = max(0, int(max_restarts))
        self._queue = []
        self._cv = threading.Condition()
        self._closed = False
        self._restarts = 0
        self._failed = None       # terminal crash after restart budget
        self._assembling = None   # batch popped but not yet dispatched
        self._thread = self._spawn_thread()

    def _spawn_thread(self):
        t = threading.Thread(target=self._run, daemon=True,
                             name="paddle-tpu-batcher")
        t.start()
        return t

    def _run(self):
        try:
            self._loop()
        except BaseException as e:   # batcher bug: recover, don't hang
            self._crash(e)

    def _crash(self, exc):
        from paddle_tpu import profiler as _profiler
        logger.exception("batcher thread crashed")
        with self._cv:
            pending, self._queue = self._queue, []
            assembling, self._assembling = self._assembling, None
            restart = not self._closed and \
                self._restarts < self.max_restarts
            if restart:
                self._restarts += 1
            elif not self._closed:
                self._failed = exc
        # record the restart BEFORE waking any waiter: "submit raised
        # BatcherCrashed" must imply "restart already observable" (the
        # counter and the live thread), or observers race the dying
        # thread's tail
        if restart:
            _profiler.runtime_metrics.inc("serving.batcher_restarts")
            self._thread = self._spawn_thread()
        err = BatcherCrashed(
            f"batcher thread crashed ({type(exc).__name__}: {exc}); "
            f"request aborted — retry")
        err.__cause__ = exc
        for p in (assembling or []) + pending:
            if not p.abandoned:
                p.error = err
                p.event.set()

    @property
    def queue_depth(self):
        with self._cv:
            return len(self._queue)

    @property
    def failed(self):
        """Terminal crash exception once the restart budget is spent
        (None while the batcher is alive) — the signal /readyz uses to
        pull a permanently-503 replica out of rotation."""
        with self._cv:
            return self._failed

    def submit(self, feed, timeout=None):
        """Enqueue one request feed and block for its outputs."""
        from paddle_tpu import profiler as _profiler
        missing = [n for n in self._predictor.feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        key, rows = batch_key(feed)
        p = _Pending(feed, key, rows)
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is shut down")
            if self._failed is not None:
                # restart budget exhausted: fail fast (still a 503 so a
                # load balancer retries a healthy replica)
                raise BatcherCrashed(
                    f"batcher is down after {self._restarts} restarts: "
                    f"{self._failed}")
            if len(self._queue) >= self.queue_size:
                _profiler.runtime_metrics.inc("serving.queue_rejections")
                raise QueueFull(
                    f"batch queue full ({self.queue_size} pending)")
            self._queue.append(p)
            self._cv.notify_all()
        if not p.event.wait(timeout):
            with self._cv:
                p.abandoned = True
                # free the queue slot NOW: a dead entry left in place
                # would count toward queue_size and shed live traffic
                try:
                    self._queue.remove(p)
                except ValueError:
                    pass  # already taken into a batch
            _profiler.runtime_metrics.inc("serving.deadline_exceeded")
            raise DeadlineExceeded(
                f"request waited more than {timeout}s for its batch")
        if p.error is not None:
            raise p.error
        return p.result

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)

    # -- batcher thread ------------------------------------------------
    def _take_compatible(self, batch, key, rows_budget):
        """Move queued requests compatible with ``key`` into ``batch``
        (holding the lock); returns the remaining row budget."""
        i = 0
        while i < len(self._queue):
            if len(batch) >= self.max_batch_size or rows_budget <= 0 or \
                    key is None:
                break
            p = self._queue[i]
            if p.abandoned:
                self._queue.pop(i)
                continue
            if p.key == key and p.rows <= rows_budget:
                self._queue.pop(i)
                batch.append(p)
                rows_budget -= p.rows
                continue
            i += 1
        return rows_budget

    def _loop(self):
        from paddle_tpu.fault import chaos
        while True:
            batch = []
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(0.05)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                first = self._queue.pop(0)
                if first.abandoned:
                    continue
                # visible to _crash: a thread death between pop and
                # scatter must fail THESE requests too, not strand them
                self._assembling = batch
                assembly_t0 = time.perf_counter()
                batch.append(first)
                budget = self.max_batch_rows - (first.rows or 0)
                # linger up to max_batch_delay for co-batchable arrivals
                deadline = time.monotonic() + self.max_batch_delay
                while first.key is not None and \
                        len(batch) < self.max_batch_size and budget > 0:
                    budget = self._take_compatible(batch, first.key, budget)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or \
                            len(batch) >= self.max_batch_size or budget <= 0:
                        break
                    self._cv.wait(remaining)
            # OUTSIDE _dispatch's per-batch try: an armed failpoint here
            # models a bug in the batcher thread itself (the per-batch
            # dispatch path already routes ITS failures to the batch)
            chaos.fire("serving.batcher.crash", size=len(batch))
            self._dispatch(batch, assembly_t0)
            with self._cv:
                self._assembling = None
                # a completed assemble->dispatch cycle is forward
                # progress: refill the restart budget (mirroring the
                # sentinel's max_rollbacks refill) so rare-but-recovered
                # crashes spread over a long uptime never accumulate
                # into a terminal outage — the budget bounds CONSECUTIVE
                # crashes, not lifetime ones
                self._restarts = 0

    def _dispatch(self, batch, assembly_t0=None):
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault import chaos
        now = time.perf_counter()
        lead = batch[0].trace_id
        for p in batch:
            # queue wait measured per request, stitched to ITS trace id
            _record_span("serving.queue_wait", p.enqueue_t,
                         now - p.enqueue_t, trace_id=p.trace_id)
        if assembly_t0 is not None:
            _record_span("serving.batch_assembly", assembly_t0,
                         now - assembly_t0, trace_id=lead,
                         size=len(batch))
        try:
            chaos.fire("serving.batch", size=len(batch))
            _profiler.runtime_metrics.bucket("serving.batch_occupancy",
                                             len(batch))
            _profiler.runtime_metrics.inc("serving.batches")
            with _trace.trace_context(lead):
                with _span("serving.dispatch", size=len(batch)):
                    results = self._predictor.run_many(
                        [p.feed for p in batch])
        except BaseException as e:
            for p in batch:
                p.error = e
                p.event.set()
            return
        with _trace.trace_context(lead):
            with _span("serving.scatter", size=len(batch)):
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()


# ---------------------------------------------------------------------------
# C-ABI bridge helpers (called from native/capi.cpp via the CPython API)
# ---------------------------------------------------------------------------

def _capi_create(model_dir):
    return Predictor(model_dir)


def _capi_feed_names(predictor):
    return predictor.feed_names


def _capi_run(predictor, names, buffers, shapes, dtypes):
    """names: list[str]; buffers: list[memoryview of raw bytes];
    shapes: list[tuple]; dtypes: list[str].  Returns
    (list[bytes], list[tuple[int]], list[str]) for the outputs."""
    feed = {}
    for name, buf, shape, dtype in zip(names, buffers, shapes, dtypes):
        feed[name] = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    outs = predictor.run(feed)
    payloads = [np.ascontiguousarray(o).tobytes() for o in outs]
    out_shapes = [tuple(int(d) for d in o.shape) for o in outs]
    out_dtypes = [str(o.dtype) for o in outs]
    return payloads, out_shapes, out_dtypes


# ---------------------------------------------------------------------------
# HTTP inference server (the serving-runtime gap in L6; JSON in/out)
# ---------------------------------------------------------------------------

class InferenceServer:
    """HTTP inference server with graceful degradation.

    - ``/healthz`` (and legacy ``/health``): liveness — 200 while the
      process serves, even before the model loads.
    - ``/readyz``: readiness — 200 only once the model is loaded; 503
      with ``retryable: true`` while loading, 500 with ``retryable:
      false`` if the load failed.
    - ``/predict`` (and alias ``/run``): 503 + ``retryable: true``
      before the model is ready or when all ``max_inflight`` slots are
      taken (load shedding), 504 + ``retryable: true`` when a request
      waits longer than ``request_timeout`` on the predictor, 400/500
      structured errors otherwise.  Every error body is
      ``{"error": {"type", "message"}, "retryable": bool}``.

    ``async_load=True`` starts serving immediately and loads the model
    in the background (k8s-style: readiness gates traffic, liveness
    doesn't kill the pod during a long restore).

    ``batching=True`` coalesces concurrent ``/predict`` requests into
    padded, row-bucketed micro-batches through a :class:`MicroBatcher`
    (one compiled dispatch per batch instead of one per request); the
    per-request 503/504 degradation semantics are preserved.
    ``warmup=True`` AOT-compiles the declared serving buckets during
    load, BEFORE ``/readyz`` flips — the first real request never pays a
    compile.  ``/stats`` serves the runtime metrics snapshot
    (``profiler.runtime_metrics``) plus server/batcher state.

    A GENERATION bundle (``gen_meta.json`` + prefill/decode programs,
    see ``paddle_tpu/gen/``) is served through ``/generate`` instead of
    ``/predict``: continuous-batching autoregressive decode with
    streamed (chunked) token responses over the same keep-alive
    connection.  ``warmup=True`` then AOT-compiles BOTH signature
    families (every prefill bucket + the decode step) before
    ``/readyz`` flips.  ``gen_admission``/``gen_queue_size`` configure
    the :class:`paddle_tpu.gen.GenScheduler`.
    """

    def __init__(self, model_dir, host="127.0.0.1", port=0,
                 async_load=False, max_inflight=32, request_timeout=None,
                 batching=False, max_batch_size=8, max_batch_delay=0.005,
                 batch_queue_size=128, warmup=False,
                 warmup_batch_sizes=None, gen_admission="continuous",
                 gen_queue_size=64, gen_prefill_budget=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from paddle_tpu.fault import chaos
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.lod import bucket_edges

        self.predictor = None
        self._gen = None          # GenScheduler for generation bundles
        self.gen_predictor = None
        self._gen_conf = {"admission": str(gen_admission),
                          "queue_size": int(gen_queue_size),
                          "prefill_budget": gen_prefill_budget}
        self._ready = threading.Event()
        self._load_done = threading.Event()  # set on success OR failure
        self._load_error = None
        # master-backed fleet membership (set by fleet.FleetReplica):
        # None = not fleet-managed, "held" = lease current, "lost" = the
        # master expired our lease while this process is alive — /readyz
        # then reports 503 lease_lost so the LB and the router agree
        self.lease_state = None
        self._slots = threading.BoundedSemaphore(max_inflight)
        self._request_timeout = request_timeout
        self._batcher = None
        self._batch_conf = {"batching": bool(batching),
                            "max_batch_size": int(max_batch_size),
                            "max_batch_delay": float(max_batch_delay),
                            "batch_queue_size": int(batch_queue_size)}
        if warmup_batch_sizes is None and warmup:
            # cover every bucket a batch of 1..max rows can pad into, so
            # no steady-state batched dispatch compiles after /readyz
            warmup_batch_sizes = bucket_edges(
                1, max(int(max_batch_size), 1)) if batching else (1,)
        self._warmup_batch_sizes = tuple(warmup_batch_sizes or ())
        self._do_warmup = bool(warmup)
        # per-bucket warmup report (compile seconds + cold/persistent-
        # hit/warm provenance), surfaced in /stats: a rolling restart's
        # "warm via compile cache" claim is observable per bucket
        self._warmup_report = None
        server = self

        def _load():
            try:
                chaos.fire("serving.load", model_dir=model_dir)
                from paddle_tpu.gen import is_gen_bundle
                if is_gen_bundle(model_dir):
                    from paddle_tpu.gen import GenPredictor, GenScheduler
                    gen_predictor = GenPredictor(model_dir)
                    if server._do_warmup:
                        chaos.fire("serving.warmup", model_dir=model_dir)
                        # both signature families — every prefill
                        # bucket AND the decode step — compile before
                        # /readyz flips
                        rep = gen_predictor.warmup()
                        server._warmup_report = getattr(
                            rep, "buckets", None)
                    server.gen_predictor = gen_predictor
                    server._gen = GenScheduler(
                        gen_predictor,
                        queue_size=server._gen_conf["queue_size"],
                        admission=server._gen_conf["admission"],
                        prefill_budget=server._gen_conf[
                            "prefill_budget"])
                    server._ready.set()
                    return
                predictor = Predictor(model_dir)
                if server._do_warmup:
                    chaos.fire("serving.warmup", model_dir=model_dir)
                    # batched dispatches see row-bucketed (padded)
                    # shapes; serialized ones see exact request shapes
                    rep = predictor.warmup(
                        server._warmup_batch_sizes or (1,),
                        bucket=server._batch_conf["batching"])
                    server._warmup_report = getattr(rep, "buckets", None)
                if server._batch_conf["batching"]:
                    server._batcher = MicroBatcher(
                        predictor,
                        max_batch_size=server._batch_conf["max_batch_size"],
                        max_batch_delay=server._batch_conf
                        ["max_batch_delay"],
                        queue_size=server._batch_conf["batch_queue_size"])
                server.predictor = predictor
                server._ready.set()
            except BaseException as e:
                server._load_error = e
            finally:
                server._load_done.set()

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: every reply carries Content-Length, so
            # closed-loop clients reuse one connection (and one server
            # thread) instead of paying connect/teardown per request
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _reply_raw(self, code, body, content_type):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                rid = getattr(self, "_request_id", None)
                if rid:
                    # echo the (accepted or generated) request id so the
                    # caller can correlate logs/traces across the hop
                    self.send_header("X-Request-Id", rid)
                self.end_headers()
                self.wfile.write(body)

            def _reply(self, code, obj):
                self._reply_raw(code, json.dumps(obj).encode(),
                                "application/json")

            def _error(self, code, etype, message, retryable):
                self._reply(code, {"error": {"type": etype,
                                             "message": message},
                                   "retryable": retryable})

            def _gate_ready(self):
                """404/503/500 preludes; returns the predictor or None
                (reply already sent)."""
                if server._load_error is not None:
                    self._error(500, "model_load_failed",
                                str(server._load_error), retryable=False)
                    return None
                if not server._ready.is_set():
                    self._error(503, "model_loading",
                                "model is still loading; retry later",
                                retryable=True)
                    return None
                return server.predictor

            def do_GET(self):
                # per-REQUEST id: a keep-alive connection reuses this
                # handler instance, so a stale id from an earlier POST
                # must not leak onto this reply (echo the caller's own
                # header when present, else no header)
                self._request_id = (self.headers.get("X-Request-Id")
                                    or "").strip() or None
                if self.path in ("/health", "/healthz"):
                    self._reply(200, {"status": "ok"})
                elif self.path == "/readyz":
                    batcher = server._batcher
                    gen = server._gen
                    if server._load_error is not None:
                        self._error(500, "model_load_failed",
                                    str(server._load_error),
                                    retryable=False)
                    elif gen is not None and gen.failed is not None:
                        # terminal scheduler death: every /generate
                        # would 503 forever — pull this replica
                        self._error(500, "scheduler_down",
                                    f"generation scheduler is down: "
                                    f"{gen.failed}", retryable=False)
                    elif batcher is not None and \
                            batcher.failed is not None:
                        # terminal batcher death: every /predict would
                        # 503 forever — stop reporting ready so the
                        # load balancer pulls this replica
                        self._error(500, "batcher_down",
                                    f"batcher is down: {batcher.failed}",
                                    retryable=False)
                    elif server.lease_state == "lost":
                        # alive and loaded, but the master expired our
                        # lease: the router already dropped us, so stop
                        # reporting ready (retryable — re-registration
                        # restores the lease without a process restart)
                        self._error(503, "lease_lost",
                                    "fleet lease expired; replica is "
                                    "out of the routing table",
                                    retryable=True)
                    elif server._ready.is_set():
                        self._reply(200, {"status": "ready"})
                    else:
                        self._error(503, "model_loading",
                                    "model is still loading",
                                    retryable=True)
                elif self.path == "/meta":
                    if server._gen is not None:
                        self._reply(200, {"generate": True,
                                          **server.gen_predictor.meta})
                        return
                    predictor = self._gate_ready()
                    if predictor is not None:
                        self._reply(200,
                                    {"feeds": predictor.feed_names,
                                     "fetches": predictor.fetch_names})
                elif self.path == "/stats":
                    snap = _profiler.runtime_metrics.snapshot()
                    batcher = server._batcher
                    snap["server"] = dict(
                        server._batch_conf,
                        ready=server._ready.is_set(),
                        request_timeout=server._request_timeout,
                        queue_depth=batcher.queue_depth if batcher else 0,
                        warmup_batch_sizes=list(
                            server._warmup_batch_sizes),
                        warmup=server._warmup_report)
                    gen = server._gen
                    if gen is not None:
                        snap["server"]["gen"] = {
                            "admission": gen.admission,
                            "queue_size": gen.queue_size,
                            "queue_depth": gen.queue_depth,
                            "active_slots": gen.active_slots,
                            "num_slots": gen.predictor.num_slots,
                            "max_len": gen.predictor.max_len,
                        }
                    self._reply(200, snap)
                elif self.path == "/metrics":
                    from paddle_tpu.obs import prom as _prom
                    self._reply_raw(
                        200, _prom.render_prometheus().encode(),
                        _prom.CONTENT_TYPE)
                elif self.path == "/trace":
                    # Chrome trace-event JSON of the span ring: load the
                    # body straight into Perfetto/chrome://tracing
                    self._reply_raw(200,
                                    _trace.dump_chrome_trace().encode(),
                                    "application/json")
                elif self.path == "/spans":
                    # raw span ring + pid/process-name/clock anchors:
                    # the scrape body fleet-level trace assembly merges
                    # (obs.aggregate.assemble_fleet_trace)
                    self._reply(200, _trace.snapshot_payload())
                else:
                    self._error(404, "not_found", self.path,
                                retryable=False)

            def do_POST(self):
                # accept the caller's X-Request-Id (generate one when
                # absent): every reply echoes it, every span of this
                # request is tagged with it — the Dapper trace-context
                # hop across the HTTP boundary
                self._request_id = (self.headers.get("X-Request-Id")
                                    or "").strip() or _trace.new_trace_id()
                # drain the body FIRST: replying on an early-error path
                # with unread body bytes would desync a keep-alive
                # connection (the next request would parse mid-body)
                if "Content-Length" not in self.headers:
                    # no declared length (absent or chunked body): the
                    # body can't be drained, so the connection can't be
                    # reused — close it after this reply
                    self.close_connection = True
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                except ValueError:
                    # unreadable length: same problem, same remedy
                    self.close_connection = True
                    self._error(400, "bad_request",
                                "invalid Content-Length header",
                                retryable=False)
                    return
                if self.path == "/generate":
                    self._handle_generate(raw)
                    return
                if self.path not in ("/predict", "/run"):
                    self._error(404, "not_found", self.path,
                                retryable=False)
                    return
                if server._gen is not None:
                    self._error(404, "not_found",
                                "generation bundle: POST /generate "
                                "instead of /predict", retryable=False)
                    return
                predictor = self._gate_ready()
                if predictor is None:
                    return
                # end-to-end deadline propagation: the caller's (or the
                # router's) remaining budget arrives as X-Deadline-Ms and
                # tightens the server-side timeout, so a retried request
                # can never spend more than the original caller allowed
                from paddle_tpu.fault.retry import parse_deadline_ms
                timeout = server._request_timeout
                try:
                    budget = parse_deadline_ms(
                        self.headers.get("X-Deadline-Ms"))
                except ValueError:
                    self._error(400, "bad_request",
                                f"invalid X-Deadline-Ms header: "
                                f"{self.headers.get('X-Deadline-Ms')!r}",
                                retryable=False)
                    return
                if budget is not None:
                    if budget <= 0:
                        _profiler.runtime_metrics.inc(
                            "serving.deadline_exceeded")
                        self._error(504, "deadline_exceeded",
                                    "caller deadline already expired",
                                    retryable=True)
                        return
                    timeout = budget if timeout is None \
                        else min(timeout, budget)
                if not server._slots.acquire(blocking=False):
                    # saturated: shed load instead of queueing unboundedly
                    self._error(503, "overloaded",
                                "all inference slots busy", retryable=True)
                    return
                t0 = time.perf_counter()
                try:
                    with _trace.trace_context(self._request_id), \
                            _span("serving.request",
                                  request_id=self._request_id,
                                  path=self.path,
                                  port=server.addr[1]):
                        chaos.fire("serving.run", path=self.path)
                        req = json.loads(raw)
                        feed = {k: np.asarray(v, dtype="float32")
                                if not isinstance(v, dict)
                                else np.asarray(v["data"],
                                                dtype=v.get("dtype",
                                                            "float32"))
                                for k, v in req["feeds"].items()}
                        if server._batcher is not None:
                            outs = server._batcher.submit(
                                feed, timeout=timeout)
                        else:
                            with _span("serving.dispatch", size=1):
                                outs = predictor.run(
                                    feed, timeout=timeout)
                        _profiler.runtime_metrics.inc(
                            "serving.requests_ok")
                    self._reply(200, {"outputs": [o.tolist() for o in outs],
                                      "shapes": [list(o.shape)
                                                 for o in outs],
                                      "dtypes": [str(o.dtype)
                                                 for o in outs]})
                except QueueFull as e:
                    self._error(503, "overloaded", str(e), retryable=True)
                except BatcherCrashed as e:
                    # the batcher died under this request and restarted:
                    # retryable by contract, same as load shedding
                    self._error(503, "batcher_restarted", str(e),
                                retryable=True)
                except DeadlineExceeded as e:
                    self._error(504, "deadline_exceeded", str(e),
                                retryable=True)
                except (ValueError, KeyError, TypeError) as e:
                    self._error(400, "bad_request", str(e), retryable=False)
                except Exception as e:
                    self._error(500, "internal", str(e), retryable=False)
                finally:
                    server._slots.release()
                    _profiler.runtime_metrics.observe(
                        "serving.request_seconds",
                        time.perf_counter() - t0)

            # -- continuous-batching generation (/generate) ------------
            def _write_chunk(self, obj):
                """One chunked-transfer ndjson line.  The
                ``gen.client.disconnect`` failpoint fires per chunk —
                an armed ``error`` simulates the client dropping
                mid-stream exactly at a write boundary (the slot-
                reclamation drill)."""
                chaos.fire("gen.client.disconnect")
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()

            def _handle_generate(self, raw):
                from paddle_tpu.fault.retry import parse_deadline_ms
                # load gates FIRST: while the loader runs we cannot yet
                # know whether this model even has a /generate, and a
                # retryable 503 keeps the router failing over instead
                # of a permanent 404 for a replica that is milliseconds
                # from ready
                if server._load_error is not None:
                    self._error(500, "model_load_failed",
                                str(server._load_error), retryable=False)
                    return
                if not server._ready.is_set():
                    self._error(503, "model_loading",
                                "model is still loading; retry later",
                                retryable=True)
                    return
                gen = server._gen
                if gen is None:
                    self._error(404, "not_found",
                                "this model has no /generate (one-shot "
                                "inference model: POST /predict)",
                                retryable=False)
                    return
                try:
                    budget = parse_deadline_ms(
                        self.headers.get("X-Deadline-Ms"))
                except ValueError:
                    self._error(400, "bad_request",
                                f"invalid X-Deadline-Ms header: "
                                f"{self.headers.get('X-Deadline-Ms')!r}",
                                retryable=False)
                    return
                timeout = server._request_timeout
                if budget is not None:
                    if budget <= 0:
                        # already expired on arrival: the immediate-504
                        # MicroBatcher contract at the generation edge
                        _profiler.runtime_metrics.inc("gen.expired")
                        self._error(504, "deadline_exceeded",
                                    "caller deadline already expired",
                                    retryable=True)
                        return
                    timeout = budget if timeout is None \
                        else min(timeout, budget)
                try:
                    req = json.loads(raw)
                    prompt = req["prompt"]
                    max_new = int(req.get("max_new_tokens", 16))
                    eos_id = req.get("eos_id")
                    do_stream = bool(req.get("stream", True))
                    # resumable sessions: resume_from=k means the
                    # prompt already carries the original prompt plus
                    # the k tokens the client holds — event indices
                    # continue at k, so the splice stays monotone and
                    # duplicate-free across replicas
                    resume_from = int(req.get("resume_from", 0) or 0)
                    if resume_from < 0:
                        raise ValueError(
                            f"resume_from must be >= 0, "
                            f"got {resume_from}")
                except (ValueError, KeyError, TypeError) as e:
                    self._error(400, "bad_request", str(e),
                                retryable=False)
                    return
                if resume_from > 0:
                    predictor = server.gen_predictor
                    eff_eos = predictor.eos_id if eos_id is None \
                        else int(eos_id)
                    try:
                        tail_tok = int(prompt[-1]) if prompt else None
                    except (TypeError, ValueError):
                        tail_tok = None
                    if tail_tok is not None and tail_tok == eff_eos:
                        # the owner died AFTER emitting EOS but before
                        # the done tail: nothing left to decode — a
                        # re-prefill here would invent tokens past EOS,
                        # so synthesize the terminal tail instead
                        self._finish_resumed_eos(do_stream, resume_from)
                        return
                    if hasattr(predictor, "can_resume") and \
                            not predictor.can_resume(len(prompt)):
                        self._error(400, "resume_unsupported",
                                    f"resumed sequence of {len(prompt)} "
                                    f"tokens exceeds this bundle's max "
                                    f"prompt length "
                                    f"{predictor.max_prompt_len}",
                                    retryable=False)
                        return
                with _trace.trace_context(self._request_id), \
                        _span("gen.request",
                              request_id=self._request_id,
                              path=self.path, port=server.addr[1]):
                    from paddle_tpu.gen import SchedulerDraining
                    try:
                        stream = gen.submit(prompt, max_new_tokens=max_new,
                                            deadline=budget, eos_id=eos_id,
                                            timeout=timeout)
                    except QueueFull as e:
                        self._error(503, "overloaded", str(e),
                                    retryable=True)
                        return
                    except SchedulerDraining as e:
                        # rolling restart in progress: retryable 503 —
                        # the router (or resume-capable client) places
                        # the session on a sibling replica
                        self._error(503, "draining", str(e),
                                    retryable=True)
                        return
                    except BatcherCrashed as e:
                        self._error(503, "scheduler_restarted", str(e),
                                    retryable=True)
                        return
                    except (ValueError, KeyError, TypeError) as e:
                        self._error(400, "bad_request", str(e),
                                    retryable=False)
                        return
                    # the reply STATUS is decided by the first event
                    # (admitted and producing vs shed), so headers wait
                    # for the first token — that instant IS the TTFT.
                    # With an explicit deadline, wait slightly PAST it:
                    # the scheduler's own expiry sweep (504 +
                    # gen.expired) is the authoritative verdict, the
                    # handler timeout only a backstop
                    first_wait = timeout
                    if budget is not None and first_wait is not None:
                        first_wait = timeout + 0.5
                    first = stream.next_event(timeout=first_wait)
                    if first is None:
                        stream.cancel()
                        _profiler.runtime_metrics.inc(
                            "serving.deadline_exceeded")
                        self._error(504, "deadline_exceeded",
                                    f"no first token within {timeout}s",
                                    retryable=True)
                        return
                    if first[0] == "error":
                        self._gen_error(first[1])
                        return
                    if first[0] == "migrate":
                        # drained while still queued: zero tokens were
                        # produced, so a plain retryable 503 IS the
                        # resume (no splice state to carry)
                        self._error(503, "draining",
                                    "replica is draining: session "
                                    "migrated before first token",
                                    retryable=True)
                        return
                    if not do_stream:
                        self._generate_buffered(stream, first,
                                                resume_from)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    if self._request_id:
                        self.send_header("X-Request-Id", self._request_id)
                    self.end_headers()
                    try:
                        # indices continue at resume_from: the monotone
                        # token_index the router/client dedupe on
                        self._write_chunk({"token": first[1],
                                           "index": resume_from})
                        index = resume_from + 1
                        while True:
                            ev = stream.next_event(timeout=300)
                            if ev is None:
                                # nobody will consume further tokens:
                                # release the KV slot too
                                stream.cancel()
                                self._write_chunk(
                                    {"error": {"type": "stalled",
                                               "message": "generation "
                                               "stalled"}, "done": True,
                                     "token_index": index,
                                     "retryable": True})
                                break
                            kind, value = ev
                            if kind == "token":
                                self._write_chunk({"token": value,
                                                   "index": index})
                                index += 1
                            elif kind == "done":
                                self._write_chunk(
                                    {"done": True,
                                     "finish_reason": value,
                                     "tokens": resume_from
                                     + len(stream.tokens),
                                     "token_index": resume_from
                                     + len(stream.tokens)})
                                break
                            elif kind == "migrate":
                                # drain-time hand-back at a token
                                # boundary: the router (or a resume-
                                # capable client) re-places the session
                                # on a survivor from exactly this index
                                self._write_chunk(
                                    {"migrate": {
                                        "resume_from": index,
                                        "remaining_tokens": value[
                                            "remaining_tokens"]},
                                     "done": True,
                                     "token_index": index,
                                     "retryable": True})
                                break
                            else:
                                self._write_chunk(
                                    {"error": {
                                        "type": type(value).__name__,
                                        "message": str(value)},
                                     "done": True,
                                     "token_index": index,
                                     "retryable":
                                         self._gen_retryable(value)})
                                break
                        self.wfile.write(b"0\r\n\r\n")
                    except (OSError, chaos.FaultInjected):
                        # the client went away mid-stream (or the
                        # disconnect drill fired): reclaim the slot and
                        # drop the connection — the decode loop must
                        # never crash on a closed socket
                        stream.cancel()
                        self.close_connection = True

            def _generate_buffered(self, stream, first, resume_from=0):
                """stream=false: collect the full generation and reply
                with a normal Content-Length body."""
                tokens = [first[1]]
                while True:
                    ev = stream.next_event(timeout=300)
                    if ev is None:
                        stream.cancel()   # free the slot: nobody reads
                        ev = ("error",
                              DeadlineExceeded("generation stalled"))
                    kind, value = ev
                    if kind == "token":
                        tokens.append(value)
                    elif kind == "done":
                        self._reply(200, {"tokens": tokens,
                                          "finish_reason": value,
                                          "done": True,
                                          "token_index": resume_from
                                          + len(tokens)})
                        return
                    elif kind == "migrate":
                        # buffered callers hold no partial state, so a
                        # retryable 503 re-runs the whole request on a
                        # survivor (greedy decode: same tokens)
                        self._error(503, "draining",
                                    "replica is draining: session "
                                    "migrated mid-generation",
                                    retryable=True)
                        return
                    else:
                        self._gen_error(value)
                        return

            def _finish_resumed_eos(self, do_stream, resume_from):
                """A resume whose prompt already ends in EOS: the owner
                died between emitting EOS and the done tail — reply the
                terminal tail directly instead of re-prefilling past
                end-of-sequence."""
                if not do_stream:
                    self._reply(200, {"tokens": [],
                                      "finish_reason": "eos",
                                      "done": True,
                                      "token_index": resume_from})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                if self._request_id:
                    self.send_header("X-Request-Id", self._request_id)
                self.end_headers()
                try:
                    self._write_chunk({"done": True,
                                       "finish_reason": "eos",
                                       "tokens": resume_from,
                                       "token_index": resume_from})
                    self.wfile.write(b"0\r\n\r\n")
                except (OSError, chaos.FaultInjected):
                    self.close_connection = True

            @staticmethod
            def _gen_retryable(exc):
                """Whether a mid-stream failure is safe to resume via
                re-prefill on a sibling replica (the tail's top-level
                ``retryable`` flag)."""
                from paddle_tpu.gen import SchedulerDraining
                return isinstance(exc, (DeadlineExceeded, QueueFull,
                                        BatcherCrashed,
                                        SchedulerDraining,
                                        ConnectionError))

            def _gen_error(self, exc):
                from paddle_tpu.gen import SchedulerDraining
                if isinstance(exc, DeadlineExceeded):
                    self._error(504, "deadline_exceeded", str(exc),
                                retryable=True)
                elif isinstance(exc, SchedulerDraining):
                    self._error(503, "draining", str(exc),
                                retryable=True)
                elif isinstance(exc, QueueFull):
                    self._error(503, "overloaded", str(exc),
                                retryable=True)
                elif isinstance(exc, BatcherCrashed):
                    self._error(503, "scheduler_restarted", str(exc),
                                retryable=True)
                elif isinstance(exc, (ValueError, KeyError, TypeError)):
                    self._error(400, "bad_request", str(exc),
                                retryable=False)
                else:
                    self._error(500, "internal", str(exc),
                                retryable=False)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._server.server_address
        if async_load:
            self._loader = threading.Thread(target=_load, daemon=True)
            self._loader.start()
        else:
            _load()
            if self._load_error is not None:
                self._server.server_close()  # don't leak the bound socket
                raise self._load_error

    @property
    def ready(self):
        return self._ready.is_set()

    @property
    def load_error(self):
        return self._load_error

    def wait_until_ready(self, timeout=None):
        """Block until the model loads.  A FAILED async load raises the
        load error instead of blocking forever; a timeout returns
        False."""
        if not self._load_done.wait(timeout):
            return False
        if self._load_error is not None:
            raise self._load_error
        return self._ready.is_set()

    def serve_forever(self):
        self._server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def drain_sessions(self, deadline_s=None):
        """Rolling-restart half-step: stop admitting new generative
        sessions, await live streams to natural completion for up to
        ``deadline_s`` seconds, then checkpoint-migrate the remainder
        at a token boundary (the handlers flush ``migrate`` tails to
        their still-open connections).  Returns the checkpoints handed
        back; a no-op (empty list) for non-generation bundles.  Call
        BEFORE :meth:`shutdown` so the tails reach the wire."""
        if self._gen is None:
            return []
        return self._gen.drain(deadline_s)

    def abort_streams(self):
        """In-process hard-kill support (chaos drills): fail every live
        generative stream with a retryable error, as an abruptly killed
        replica would.  No-op for non-generation bundles."""
        if self._gen is not None:
            self._gen.abort_streams()

    def shutdown(self):
        # stop accepting FIRST: closing the batcher while handlers are
        # still arriving would turn their requests into non-retryable
        # 500s; close() then drains what is already queued
        self._server.shutdown()
        if self._batcher is not None:
            self._batcher.close()
        if self._gen is not None:
            self._gen.close()
        self._server.server_close()


def _history_with_hints(history, hints):
    """The per-attempt base-URL trail with ``retry-after=<s>s``
    annotations appended to the attempts whose replies carried a
    ``Retry-After`` hint — RetryError.history is the forensic record of
    a failed failover chain, and *who told us to back off, by how much*
    is part of it.  Attempts without a hint stay plain base strings
    (tests and failover bookkeeping compare those verbatim)."""
    out = []
    for i, base in enumerate(history):
        hint = hints.get(i)
        out.append(base if hint is None
                   else f"{base} retry-after={hint:g}s")
    return out


class ServingClient:
    """Retrying client for :class:`InferenceServer` — optionally a
    client-side load balancer over a replica fleet.

    Transport failures AND replies the server marks ``retryable: true``
    (model still loading, load shedding, deadline exceeded) are retried
    under ``retry`` (a :class:`paddle_tpu.fault.RetryPolicy`); permanent
    errors raise :class:`ServingError` immediately.  This is the
    trainer/edge-side mirror of the master RPC retry path: a briefly
    unready or saturated server no longer kills the caller.

    ``addr`` may be one ``host:port`` or a LIST of them: requests then
    round-robin across the replicas and every retry prefers a replica
    that has not failed this request yet (client-side failover).  With
    ``master=`` the replica list is discovered live from a
    :class:`paddle_tpu.parallel.master.MasterService` (lease-expired
    replicas drop out on the next refresh).  Exhausted retries raise
    :class:`paddle_tpu.fault.RetryError` with ``.history`` holding the
    per-attempt replica bases — the forensic trail of a failed
    failover chain.

    Idempotency/traceability: every logical request carries ONE
    ``X-Request-Id`` (the ambient trace id when set, else freshly
    minted) across ALL its retry attempts, so replicas and the router
    can recognize — and operators can trace — the same request as it
    fails over.  Pre-dispatch connection errors (reset/refused before a
    reply line) are always retryable: the server has not dispatched
    anything, so re-sending is safe.
    """

    def __init__(self, addr=None, retry=None, timeout=30.0, master=None,
                 refresh_interval=1.0, deadline=None):
        from paddle_tpu.fault.retry import RetryPolicy, parse_hostport
        if addr is None and master is None:
            raise ValueError("ServingClient needs addr(s) or master=")
        # end-to-end budget (seconds) for one LOGICAL request including
        # every retry: each attempt ships the remaining budget as
        # X-Deadline-Ms (the router forwards it, the replica's batcher
        # bounds its wait by it) and the retry chain is cut when the
        # budget can't cover the next backoff
        self._deadline = None if deadline is None else float(deadline)
        if addr is None:
            addrs = []
        elif isinstance(addr, list):
            addrs = list(addr)
        elif isinstance(addr, tuple) and len(addr) == 2 and \
                (isinstance(addr[1], int) or str(addr[1]).isdigit()):
            addrs = [addr]          # one (host, port) pair
        elif isinstance(addr, tuple):
            addrs = list(addr)      # a tuple OF addresses
        else:
            addrs = [addr]
        self._bases = []
        for a in addrs:
            host, port = parse_hostport(a)
            self._bases.append(f"http://{host}:{port}")
        self._timeout = timeout
        self._retry = retry or RetryPolicy(max_attempts=8, base_delay=0.1,
                                           max_delay=2.0, deadline=60.0)
        self._lock = threading.Lock()
        self._rr = 0
        self._master_addr = master
        self._master = None
        self._refresh_interval = float(refresh_interval)
        self._refreshed_at = 0.0

    # kept for back-compat introspection (single-replica callers)
    @property
    def _base(self):
        bases = self._live_bases()
        return bases[0] if bases else None

    def _live_bases(self):
        """Current replica bases, refreshing from the master when one is
        configured and the cached list is stale (or empty)."""
        if self._master_addr is None:
            return list(self._bases)
        now = time.monotonic()
        with self._lock:
            stale = now - self._refreshed_at > self._refresh_interval
            cached = list(self._bases)
        if not stale and cached:
            return cached
        try:
            with self._lock:
                if self._master is None:
                    from paddle_tpu.parallel.master import MasterClient
                    self._master = MasterClient(self._master_addr)
                master = self._master
            live = master.list_replicas()
            from paddle_tpu.fault.retry import parse_hostport
            bases = []
            for rec in live:
                host, port = parse_hostport(rec["addr"])
                bases.append(f"http://{host}:{port}")
            with self._lock:
                self._bases = bases
                self._refreshed_at = now
            return bases
        except Exception:
            # master briefly unreachable: serve from the cached list —
            # and back off (stamp the refresh time) so the request hot
            # path doesn't re-dial the dead master on every attempt
            with self._lock:
                self._refreshed_at = now
            return cached

    def _pick_base(self, tried):
        """Round-robin over live bases, preferring one not yet tried by
        THIS request (failover targets a *different* replica while any
        remain)."""
        bases = self._live_bases()
        if not bases:
            raise ConnectionError("no live serving replicas")
        with self._lock:
            self._rr += 1
            start = self._rr
        untried = [b for b in bases if b not in tried]
        pool = untried or bases
        return pool[start % len(pool)]

    def _request(self, path, payload=None, retry=True):
        import urllib.error
        import urllib.request
        from paddle_tpu.fault.retry import RetryError

        # ONE id per logical request, reused verbatim by every retry
        # attempt (idempotency key + the trace the failover chain shares)
        rid = _trace.current_trace_id() or _trace.new_trace_id()
        history = []
        hints = {}      # attempt index -> Retry-After seconds
        deadline_at = None if self._deadline is None \
            else time.monotonic() + self._deadline

        def attempt():
            from paddle_tpu.fault.retry import parse_retry_after
            base = self._pick_base(history)
            history.append(base)
            headers = {"Content-Type": "application/json",
                       "X-Request-Id": rid}
            timeout = self._timeout
            if deadline_at is not None:
                remaining = max(deadline_at - time.monotonic(), 0.001)
                headers["X-Deadline-Ms"] = str(int(remaining * 1000) or 1)
                # one hung attempt must not outlive the logical budget
                timeout = min(timeout, remaining)
            req = urllib.request.Request(
                base + path,
                data=None if payload is None
                else json.dumps(payload).encode(),
                headers=headers)
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read())
                except ValueError:
                    body = {"error": {"type": "http", "message": str(e)},
                            "retryable": e.code in (429, 502, 503, 504)}
                err = body.get("error") or {}
                if body.get("retryable"):
                    exc = _TransientServingError(
                        f"{err.get('type', 'http')}: "
                        f"{err.get('message', str(e))}")
                    hint = parse_retry_after(
                        e.headers.get("Retry-After")
                        if e.headers is not None else None)
                    if hint is not None:
                        # server-paced: the retry policy sleeps this
                        # instead of its own backoff
                        exc.retry_after = hint
                        hints[len(history) - 1] = hint
                    raise exc from e
                raise ServingError(err.get("type", "http"),
                                   err.get("message", str(e)),
                                   retryable=False) from e
            except urllib.error.URLError as e:
                # pre-dispatch transport failure (refused/reset before a
                # reply): nothing reached a batcher, re-sending under the
                # same X-Request-Id is safe — always retryable
                raise ConnectionError(str(e)) from e

        try:
            if not retry:
                return attempt()
            # deadline=None falls back to the policy's own budget
            return self._retry.call(attempt, deadline=self._deadline)
        except RetryError as e:
            e.history = _history_with_hints(history, hints)
            raise

    def predict(self, feeds):
        """feeds: dict name -> array-like; returns list of ndarrays."""
        resp = self._request("/predict", {
            "feeds": {k: np.asarray(v).tolist() for k, v in feeds.items()}})
        dtypes = resp.get("dtypes") or [None] * len(resp["outputs"])
        return [np.asarray(o) if dt is None else np.asarray(o, dtype=dt)
                for o, dt in zip(resp["outputs"], dtypes)]

    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 stream=True, retry=True, session_id=None, resume=True,
                 max_resumes=8):
        """Stream a generation from ``/generate``: returns an iterator
        of parsed ndjson events — ``{"token": id, "index": i}`` per
        produced token, then ``{"done": true, "finish_reason": ...}``
        (or ``{"error": ..., "done": true}`` if the stream failed
        mid-flight).  Chunks are yielded AS THEY ARRIVE, so the first
        token is available while the server is still decoding.

        Pre-stream failures (connection errors, retryable 503/504
        replies) retry/fail over under the client's policy like
        ``predict``.  MID-stream failures are resumable (``resume=``,
        the router-less failover path): on a dead socket, a torn
        chunk, a retryable error tail, or a drain-time ``migrate``
        tail, the client re-submits ``prompt + tokens_so_far`` with a
        ``resume_from`` index to a (preferably different) replica and
        splices the continuation, deduplicating on each event's
        monotone ``token_index`` — greedy decode is deterministic, so
        the client-visible sequence is identical to an unbroken
        stream.  A NON-retryable mid-stream failure (or ``resume=
        False``, or ``max_resumes`` exhausted) surfaces as the
        documented terminal error event, never as a raw exception out
        of the iterator."""
        import http.client
        from paddle_tpu.fault.retry import RetryError, parse_hostport

        rid = _trace.current_trace_id() or _trace.new_trace_id()
        if session_id is None:
            from paddle_tpu.fleet.sessions import new_session_id
            session_id = new_session_id()
        orig_prompt = [int(t) for t in prompt]
        max_new = int(max_new_tokens)
        toks = []       # tokens delivered to the caller so far
        history = []
        hints = {}      # attempt index -> Retry-After seconds
        deadline_at = None if self._deadline is None \
            else time.monotonic() + self._deadline

        def payload():
            p = {"prompt": orig_prompt + toks,
                 "max_new_tokens": max_new - len(toks),
                 "stream": bool(stream),
                 "session_id": session_id}
            if toks:
                p["resume_from"] = len(toks)
            if eos_id is not None:
                p["eos_id"] = int(eos_id)
            return p

        def attempt():
            from paddle_tpu.fault.retry import parse_retry_after
            base = self._pick_base(history)
            history.append(base)
            host, port = parse_hostport(base[len("http://"):])
            headers = {"Content-Type": "application/json",
                       "X-Request-Id": rid}
            timeout = self._timeout
            if deadline_at is not None:
                remaining = max(deadline_at - time.monotonic(), 0.001)
                headers["X-Deadline-Ms"] = str(int(remaining * 1000) or 1)
                timeout = min(timeout, remaining)
            body = json.dumps(payload()).encode()
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            try:
                conn.request("POST", "/generate", body, headers)
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                raise ConnectionError(str(e)) from e
            if resp.status != 200:
                data = resp.read()
                hint = parse_retry_after(resp.getheader("Retry-After"))
                conn.close()
                try:
                    parsed = json.loads(data)
                except ValueError:
                    parsed = {"retryable":
                              resp.status in (429, 502, 503, 504)}
                err = parsed.get("error") or {}
                if parsed.get("retryable"):
                    exc = _TransientServingError(
                        f"{err.get('type', 'http')}: "
                        f"{err.get('message', resp.status)}")
                    if hint is not None:
                        exc.retry_after = hint
                        hints[len(history) - 1] = hint
                    raise exc
                raise ServingError(err.get("type", "http"),
                                   err.get("message", str(resp.status)),
                                   retryable=False)
            return conn, resp

        def connect():
            if retry:
                return self._retry.call(attempt,
                                        deadline=self._deadline)
            return attempt()

        try:
            conn, resp = connect()
        except RetryError as e:
            e.history = _history_with_hints(history, hints)
            raise

        def events():
            import http.client

            from paddle_tpu import profiler as _profiler
            nonlocal conn, resp
            resumes = 0
            resumable = bool(resume) and stream
            try:
                while True:
                    failure = None
                    obj = None
                    try:
                        line = resp.readline()
                        if not line:
                            if not resumable:
                                return      # legacy: silent clean EOF
                            failure = ConnectionError(
                                "stream closed without a terminal "
                                "event")
                        else:
                            obj = json.loads(line)
                    except (OSError, http.client.HTTPException,
                            ValueError) as e:
                        failure = e
                    if failure is None:
                        if "token" in obj and "index" in obj:
                            idx = obj["index"]
                            if idx < len(toks):
                                # replayed prefix after a resume: the
                                # exactly-once guarantee is THIS drop
                                _profiler.runtime_metrics.inc(
                                    "gen.session.dedup_drops")
                                continue
                            if idx == len(toks):
                                toks.append(int(obj["token"]))
                                yield obj
                                continue
                            # an index GAP means tokens were torn out
                            # of the transport: resume from what we
                            # actually hold
                            failure = ConnectionError(
                                f"token_index gap: got {idx}, "
                                f"expected {len(toks)}")
                        elif obj.get("done") and "migrate" in obj:
                            failure = ConnectionError(
                                "session migrated (replica draining)")
                        elif obj.get("done") and obj.get("error") \
                                and obj.get("retryable") and resumable:
                            failure = ConnectionError(
                                f"retryable mid-stream error tail: "
                                f"{obj['error'].get('type')}")
                        else:
                            yield obj
                            if obj.get("done"):
                                return
                            continue
                    # a resumable fault: re-submit prompt + toks with
                    # resume_from and splice the continuation
                    if not resumable or resumes >= max_resumes:
                        yield {"error": {"type": type(failure).__name__,
                                         "message": str(failure)},
                               "done": True,
                               "token_index": len(toks),
                               "retryable": True}
                        return
                    try:
                        conn.close()
                    except Exception:
                        pass
                    try:
                        conn, resp = connect()
                    except (RetryError, ServingError,
                            ConnectionError) as e:
                        yield {"error": {"type": type(e).__name__,
                                         "message": str(e)},
                               "done": True,
                               "token_index": len(toks),
                               "retryable": not isinstance(
                                   e, ServingError)}
                        return
                    resumes += 1
                    _profiler.runtime_metrics.inc("gen.session.resumes")
            finally:
                conn.close()

        return events()

    def meta(self):
        return self._request("/meta")

    def stats(self):
        """Runtime metrics snapshot (/stats): request latency
        percentiles, batch occupancy, compile/jit-cache counters."""
        return self._request("/stats")

    def trace(self):
        """The server's span ring as a Chrome trace-event JSON object
        (/trace) — save it and load into Perfetto."""
        return self._request("/trace")

    def prom_metrics(self):
        """The server's /metrics body: Prometheus text exposition of
        the runtime metrics registry (plain text, not JSON)."""
        import urllib.request
        base = self._base
        if base is None:
            raise ConnectionError("no live serving replicas")
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=self._timeout) as r:
            return r.read().decode()

    def close(self):
        """Release the master discovery connection (no-op without
        ``master=``)."""
        with self._lock:
            master, self._master = self._master, None
        if master is not None:
            master.close()

    def healthy(self):
        """Single-shot liveness probe (no retries — probes must be cheap)."""
        try:
            return self._request("/healthz",
                                 retry=False).get("status") == "ok"
        except Exception:
            return False

    def ready(self):
        """Single-shot readiness probe."""
        try:
            return self._request("/readyz",
                                 retry=False).get("status") == "ready"
        except Exception:
            return False


def serve(model_dir, host="127.0.0.1", port=8866, async_load=False,
          max_inflight=32, request_timeout=None, batching=False,
          max_batch_size=8, max_batch_delay=0.005, batch_queue_size=128,
          warmup=False, warmup_batch_sizes=None,
          gen_admission="continuous", gen_queue_size=64):
    server = InferenceServer(model_dir, host, port, async_load=async_load,
                             max_inflight=max_inflight,
                             request_timeout=request_timeout,
                             batching=batching,
                             max_batch_size=max_batch_size,
                             max_batch_delay=max_batch_delay,
                             batch_queue_size=batch_queue_size,
                             warmup=warmup,
                             warmup_batch_sizes=warmup_batch_sizes,
                             gen_admission=gen_admission,
                             gen_queue_size=gen_queue_size)
    print(f"serving {model_dir} on {server.addr[0]}:{server.addr[1]}",
          flush=True)
    server.serve_forever()
