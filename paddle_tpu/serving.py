"""Serving runtime: Predictor, HTTP inference server, and the C-ABI
helpers behind ``native/capi.cpp``.

Reference L6 surface: the C++ inference loader (``inference/io.h:35`` +
``inference/tests/book``) and the embeddable pure-C ABI
(``paddle/capi/capi.h`` ``paddle_gradient_machine_*``).  TPU re-design:
the compute runs through XLA/PJRT either way; the native shell
(``native/capi.cpp``) embeds CPython to drive this module — the mirror
image of the reference, which embedded CPython in its C++ data layer
(``PyDataProvider2.cpp``)."""

from __future__ import annotations

import json
import threading

import numpy as np

__all__ = ["Predictor", "serve", "InferenceServer", "DeadlineExceeded",
           "ServingClient", "ServingError"]


class DeadlineExceeded(RuntimeError):
    """A request timed out waiting for the predictor (queue saturation)."""


class ServingError(RuntimeError):
    """Structured server-side error; ``retryable`` mirrors the reply."""

    def __init__(self, etype, message, retryable=False):
        super().__init__(f"{etype}: {message}")
        self.etype = etype
        self.retryable = retryable


class _TransientServingError(ConnectionError):
    """A retryable (503/504) reply, surfaced as a transport-class error
    so RetryPolicy's default ``retryable`` set covers it."""


class Predictor:
    """Load-once, run-many inference handle over a saved inference model
    (the ``paddle_gradient_machine`` analog)."""

    def __init__(self, model_dir):
        import paddle_tpu as fluid

        self._fluid = fluid
        self._scope = fluid.Scope()
        self._lock = threading.Lock()  # Executor/scope are not re-entrant
        with fluid.scope_guard(self._scope):
            self._exe = fluid.Executor()
            (self._program, self._feed_names,
             self._fetch_targets) = fluid.io.load_inference_model(
                model_dir, self._exe)

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return [t.name if hasattr(t, "name") else str(t)
                for t in self._fetch_targets]

    def run(self, feed, timeout=None):
        """feed: dict name -> ndarray; returns list of ndarrays.

        ``timeout``: max seconds to wait for the (serialized) executor —
        a saturated predictor raises :class:`DeadlineExceeded` instead of
        queueing the caller indefinitely."""
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        if not self._lock.acquire(timeout=-1 if timeout is None
                                  else timeout):
            raise DeadlineExceeded(
                f"predictor busy for more than {timeout}s")
        try:
            with self._fluid.scope_guard(self._scope):
                outs = self._exe.run(self._program, feed=dict(feed),
                                     fetch_list=self._fetch_targets)
        finally:
            self._lock.release()
        return [np.asarray(o) for o in outs]


# ---------------------------------------------------------------------------
# C-ABI bridge helpers (called from native/capi.cpp via the CPython API)
# ---------------------------------------------------------------------------

def _capi_create(model_dir):
    return Predictor(model_dir)


def _capi_feed_names(predictor):
    return predictor.feed_names


def _capi_run(predictor, names, buffers, shapes, dtypes):
    """names: list[str]; buffers: list[memoryview of raw bytes];
    shapes: list[tuple]; dtypes: list[str].  Returns
    (list[bytes], list[tuple[int]], list[str]) for the outputs."""
    feed = {}
    for name, buf, shape, dtype in zip(names, buffers, shapes, dtypes):
        feed[name] = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    outs = predictor.run(feed)
    payloads = [np.ascontiguousarray(o).tobytes() for o in outs]
    out_shapes = [tuple(int(d) for d in o.shape) for o in outs]
    out_dtypes = [str(o.dtype) for o in outs]
    return payloads, out_shapes, out_dtypes


# ---------------------------------------------------------------------------
# HTTP inference server (the serving-runtime gap in L6; JSON in/out)
# ---------------------------------------------------------------------------

class InferenceServer:
    """HTTP inference server with graceful degradation.

    - ``/healthz`` (and legacy ``/health``): liveness — 200 while the
      process serves, even before the model loads.
    - ``/readyz``: readiness — 200 only once the model is loaded; 503
      with ``retryable: true`` while loading, 500 with ``retryable:
      false`` if the load failed.
    - ``/predict`` (and alias ``/run``): 503 + ``retryable: true``
      before the model is ready or when all ``max_inflight`` slots are
      taken (load shedding), 504 + ``retryable: true`` when a request
      waits longer than ``request_timeout`` on the predictor, 400/500
      structured errors otherwise.  Every error body is
      ``{"error": {"type", "message"}, "retryable": bool}``.

    ``async_load=True`` starts serving immediately and loads the model
    in the background (k8s-style: readiness gates traffic, liveness
    doesn't kill the pod during a long restore).
    """

    def __init__(self, model_dir, host="127.0.0.1", port=0,
                 async_load=False, max_inflight=32, request_timeout=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from paddle_tpu.fault import chaos

        self.predictor = None
        self._ready = threading.Event()
        self._load_done = threading.Event()  # set on success OR failure
        self._load_error = None
        self._slots = threading.BoundedSemaphore(max_inflight)
        self._request_timeout = request_timeout
        server = self

        def _load():
            try:
                chaos.fire("serving.load", model_dir=model_dir)
                server.predictor = Predictor(model_dir)
                server._ready.set()
            except BaseException as e:
                server._load_error = e
            finally:
                server._load_done.set()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code, etype, message, retryable):
                self._reply(code, {"error": {"type": etype,
                                             "message": message},
                                   "retryable": retryable})

            def _gate_ready(self):
                """404/503/500 preludes; returns the predictor or None
                (reply already sent)."""
                if server._load_error is not None:
                    self._error(500, "model_load_failed",
                                str(server._load_error), retryable=False)
                    return None
                if not server._ready.is_set():
                    self._error(503, "model_loading",
                                "model is still loading; retry later",
                                retryable=True)
                    return None
                return server.predictor

            def do_GET(self):
                if self.path in ("/health", "/healthz"):
                    self._reply(200, {"status": "ok"})
                elif self.path == "/readyz":
                    if server._load_error is not None:
                        self._error(500, "model_load_failed",
                                    str(server._load_error),
                                    retryable=False)
                    elif server._ready.is_set():
                        self._reply(200, {"status": "ready"})
                    else:
                        self._error(503, "model_loading",
                                    "model is still loading",
                                    retryable=True)
                elif self.path == "/meta":
                    predictor = self._gate_ready()
                    if predictor is not None:
                        self._reply(200,
                                    {"feeds": predictor.feed_names,
                                     "fetches": predictor.fetch_names})
                else:
                    self._error(404, "not_found", self.path,
                                retryable=False)

            def do_POST(self):
                if self.path not in ("/predict", "/run"):
                    self._error(404, "not_found", self.path,
                                retryable=False)
                    return
                predictor = self._gate_ready()
                if predictor is None:
                    return
                if not server._slots.acquire(blocking=False):
                    # saturated: shed load instead of queueing unboundedly
                    self._error(503, "overloaded",
                                "all inference slots busy", retryable=True)
                    return
                try:
                    chaos.fire("serving.run", path=self.path)
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    feed = {k: np.asarray(v, dtype="float32")
                            if not isinstance(v, dict)
                            else np.asarray(v["data"],
                                            dtype=v.get("dtype", "float32"))
                            for k, v in req["feeds"].items()}
                    outs = predictor.run(
                        feed, timeout=server._request_timeout)
                    self._reply(200, {"outputs": [o.tolist() for o in outs],
                                      "shapes": [list(o.shape)
                                                 for o in outs],
                                      "dtypes": [str(o.dtype)
                                                 for o in outs]})
                except DeadlineExceeded as e:
                    self._error(504, "deadline_exceeded", str(e),
                                retryable=True)
                except (ValueError, KeyError, TypeError) as e:
                    self._error(400, "bad_request", str(e), retryable=False)
                except Exception as e:
                    self._error(500, "internal", str(e), retryable=False)
                finally:
                    server._slots.release()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.addr = self._server.server_address
        if async_load:
            self._loader = threading.Thread(target=_load, daemon=True)
            self._loader.start()
        else:
            _load()
            if self._load_error is not None:
                self._server.server_close()  # don't leak the bound socket
                raise self._load_error

    @property
    def ready(self):
        return self._ready.is_set()

    @property
    def load_error(self):
        return self._load_error

    def wait_until_ready(self, timeout=None):
        """Block until the model loads.  A FAILED async load raises the
        load error instead of blocking forever; a timeout returns
        False."""
        if not self._load_done.wait(timeout):
            return False
        if self._load_error is not None:
            raise self._load_error
        return self._ready.is_set()

    def serve_forever(self):
        self._server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class ServingClient:
    """Retrying client for :class:`InferenceServer`.

    Transport failures AND replies the server marks ``retryable: true``
    (model still loading, load shedding, deadline exceeded) are retried
    under ``retry`` (a :class:`paddle_tpu.fault.RetryPolicy`); permanent
    errors raise :class:`ServingError` immediately.  This is the
    trainer/edge-side mirror of the master RPC retry path: a briefly
    unready or saturated server no longer kills the caller.
    """

    def __init__(self, addr, retry=None, timeout=30.0):
        from paddle_tpu.fault.retry import RetryPolicy, parse_hostport
        host, port = parse_hostport(addr)
        self._base = f"http://{host}:{port}"
        self._timeout = timeout
        self._retry = retry or RetryPolicy(max_attempts=8, base_delay=0.1,
                                           max_delay=2.0, deadline=60.0)

    def _request(self, path, payload=None, retry=True):
        import urllib.error
        import urllib.request

        def attempt():
            req = urllib.request.Request(
                self._base + path,
                data=None if payload is None
                else json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(
                        req, timeout=self._timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read())
                except ValueError:
                    body = {"error": {"type": "http", "message": str(e)},
                            "retryable": e.code in (502, 503, 504)}
                err = body.get("error") or {}
                if body.get("retryable"):
                    raise _TransientServingError(
                        f"{err.get('type', 'http')}: "
                        f"{err.get('message', str(e))}") from e
                raise ServingError(err.get("type", "http"),
                                   err.get("message", str(e)),
                                   retryable=False) from e
            except urllib.error.URLError as e:
                raise ConnectionError(str(e)) from e

        return self._retry.call(attempt) if retry else attempt()

    def predict(self, feeds):
        """feeds: dict name -> array-like; returns list of ndarrays."""
        resp = self._request("/predict", {
            "feeds": {k: np.asarray(v).tolist() for k, v in feeds.items()}})
        dtypes = resp.get("dtypes") or [None] * len(resp["outputs"])
        return [np.asarray(o) if dt is None else np.asarray(o, dtype=dt)
                for o, dt in zip(resp["outputs"], dtypes)]

    def meta(self):
        return self._request("/meta")

    def healthy(self):
        """Single-shot liveness probe (no retries — probes must be cheap)."""
        try:
            return self._request("/healthz",
                                 retry=False).get("status") == "ok"
        except Exception:
            return False

    def ready(self):
        """Single-shot readiness probe."""
        try:
            return self._request("/readyz",
                                 retry=False).get("status") == "ready"
        except Exception:
            return False


def serve(model_dir, host="127.0.0.1", port=8866, async_load=False,
          max_inflight=32, request_timeout=None):
    server = InferenceServer(model_dir, host, port, async_load=async_load,
                             max_inflight=max_inflight,
                             request_timeout=request_timeout)
    print(f"serving {model_dir} on {server.addr[0]}:{server.addr[1]}",
          flush=True)
    server.serve_forever()
