"""DataFeeder: python lists/numpy -> feed dict with LoD handling
(reference ``python/paddle/fluid/data_feeder.py:69``:
``DataToLoDTensorConverter:25``).

Ragged (lod_level>0) slots are converted to (flattened_values,
recursive_sequence_lengths) pairs; the executor stores the row-splits next
to the array (see ``paddle_tpu.lod``).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Variable

__all__ = ["DataFeeder", "FeedShapeError"]


class FeedShapeError(ValueError):
    """Fed samples cannot be reshaped to a slot's declared shape (the
    PADDLE_ENFORCE analog at the feeder boundary — raised HERE, with the
    slot named, instead of letting a mis-shaped array surface as a deep
    trace error downstream)."""


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype, name=None):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.name = name
        self.dtype = np.dtype(dtype) if dtype != "bfloat16" else dtype
        self.reset()

    def reset(self):
        """Clear accumulated samples so the converter can be reused for
        the next batch (DataFeeder caches converters across feed calls)."""
        self.data = []
        self.lod = [[] for _ in range(self.lod_level)]

    def _check_dtype(self, stacked):
        """Reject float samples headed into an integer slot: the dtype
        cast below would silently TRUNCATE them — the classic
        mis-wired-feed bug (labels and features swapped) that then
        trains on garbage without a peep.  ``stacked`` is the batch
        array built WITHOUT a forced dtype, so even one float sample in
        an otherwise-integer batch promotes its kind and is caught."""
        if not isinstance(self.dtype, np.dtype):
            return  # bfloat16 string tag: no integer truncation risk
        if stacked.dtype.kind in "fc" and self.dtype.kind in "iub":
            raise FeedShapeError(
                f"feed slot {self.name or '<unnamed>'!r}: got "
                f"{stacked.dtype.name} samples for a declared "
                f"{self.dtype.name} slot — refusing the silent "
                f"truncating cast (fix the feed order or the declared "
                f"dtype)")

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def _needs_truncation_check(self):
        # only integer/bool targets can silently truncate; float slots
        # keep the single cast-while-stacking path (no double convert
        # on the hot feed loop)
        return isinstance(self.dtype, np.dtype) and self.dtype.kind in "iub"

    def done(self):
        if self.lod_level == 0:
            if self.data and self._needs_truncation_check():
                # stack WITHOUT the target dtype first: mixed batches
                # promote (one float sample makes the whole batch kind
                # 'f'), so the truncation check sees every sample
                arr = np.asarray(self.data)
                self._check_dtype(arr)
                arr = arr.astype(self.dtype, copy=False)
            else:
                arr = np.array(self.data, dtype=self.dtype)
            inner = [d for d in self.shape[1:]] if self.shape else []
            # the strict reshape only makes sense when every non-batch
            # dim is concrete; with dynamic inner dims (-1/None) the
            # stacked sample array is already the right shape
            if inner and all(d is not None and d >= 0 for d in inner):
                try:
                    arr = arr.reshape([-1] + inner)
                except ValueError as e:
                    # a silent pass here used to feed the mis-shaped array
                    # downstream, failing much later inside a trace; name
                    # the slot and fail at the boundary instead
                    raise FeedShapeError(
                        f"feed slot {self.name or '<unnamed>'!r}: "
                        f"{len(self.data)} sample(s) with total shape "
                        f"{arr.shape} cannot be reshaped to declared "
                        f"shape {tuple(self.shape)}: {e}") from e
            return arr
        flat = []

        def _flatten(x):
            if isinstance(x, (list, tuple)):
                for e in x:
                    _flatten(e)
            else:
                flat.append(x)

        _flatten(self.data)
        if flat and self._needs_truncation_check():
            arr = np.asarray(flat)
            self._check_dtype(arr)
            arr = arr.astype(self.dtype, copy=False)
        else:
            arr = np.array(flat, dtype=self.dtype)
        inner = [d for d in self.shape if d != -1]
        if inner:
            arr = arr.reshape([-1] + inner)
        return (arr, self.lod)


class DataFeeder:
    """reference ``data_feeder.py:69``."""

    def __init__(self, feed_list, place, program=None):
        from paddle_tpu.framework import default_main_program
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(each_var.dtype)
        self.place = place
        self._converters = None
        self._feeding = False

    def feed(self, iterable):
        """Convert one batch of samples to a feed dict.

        NOT re-entrant: the converter set is cached across calls (built
        once, reset per batch), so one DataFeeder serves one feeding
        thread — overlapping calls would interleave two batches into
        one output array.  Concurrent misuse raises instead."""
        if self._feeding:
            raise RuntimeError(
                "DataFeeder.feed is not re-entrant (converters are "
                "cached across calls); use one DataFeeder per feeding "
                "thread")
        self._feeding = True
        try:
            return self._feed(iterable)
        finally:
            self._feeding = False

    def _feed(self, iterable):
        # converters are built once and reset per batch — the per-feed
        # construction cost (np.dtype parsing, per-slot allocation) used
        # to be paid on EVERY batch of the training loop
        if self._converters is None:
            self._converters = [
                DataToLoDTensorConverter(self.place, lod_level=lod,
                                         shape=shape, dtype=dtype, name=name)
                for lod, shape, dtype, name in zip(self.feed_lod_level,
                                                   self.feed_shapes,
                                                   self.feed_dtypes,
                                                   self.feed_names)]
        else:
            for conv in self._converters:
                conv.reset()
        converters = self._converters
        for each_sample in iterable:
            assert len(each_sample) == len(converters), \
                "sample arity != feed arity"
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}
