"""DataFeeder: python lists/numpy -> feed dict with LoD handling
(reference ``python/paddle/fluid/data_feeder.py:69``:
``DataToLoDTensorConverter:25``).

Ragged (lod_level>0) slots are converted to (flattened_values,
recursive_sequence_lengths) pairs; the executor stores the row-splits next
to the array (see ``paddle_tpu.lod``).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Variable

__all__ = ["DataFeeder", "FeedShapeError"]


class FeedShapeError(ValueError):
    """Fed samples cannot be reshaped to a slot's declared shape (the
    PADDLE_ENFORCE analog at the feeder boundary — raised HERE, with the
    slot named, instead of letting a mis-shaped array surface as a deep
    trace error downstream)."""


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype, name=None):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.name = name
        self.dtype = np.dtype(dtype) if dtype != "bfloat16" else dtype
        self.data = []
        self.lod = [[] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            inner = [d for d in self.shape[1:]] if self.shape else []
            # the strict reshape only makes sense when every non-batch
            # dim is concrete; with dynamic inner dims (-1/None) the
            # stacked sample array is already the right shape
            if inner and all(d is not None and d >= 0 for d in inner):
                try:
                    arr = arr.reshape([-1] + inner)
                except ValueError as e:
                    # a silent pass here used to feed the mis-shaped array
                    # downstream, failing much later inside a trace; name
                    # the slot and fail at the boundary instead
                    raise FeedShapeError(
                        f"feed slot {self.name or '<unnamed>'!r}: "
                        f"{len(self.data)} sample(s) with total shape "
                        f"{arr.shape} cannot be reshaped to declared "
                        f"shape {tuple(self.shape)}: {e}") from e
            return arr
        flat = []

        def _flatten(x):
            if isinstance(x, (list, tuple)):
                for e in x:
                    _flatten(e)
            else:
                flat.append(x)

        _flatten(self.data)
        arr = np.array(flat, dtype=self.dtype)
        inner = [d for d in self.shape if d != -1]
        if inner:
            arr = arr.reshape([-1] + inner)
        return (arr, self.lod)


class DataFeeder:
    """reference ``data_feeder.py:69``."""

    def __init__(self, feed_list, place, program=None):
        from paddle_tpu.framework import default_main_program
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        program = program or default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(each_var.dtype)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(self.place, lod_level=lod, shape=shape,
                                     dtype=dtype, name=name)
            for lod, shape, dtype, name in zip(self.feed_lod_level,
                                               self.feed_shapes,
                                               self.feed_dtypes,
                                               self.feed_names)]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), \
                "sample arity != feed arity"
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}
