// recordio: chunked, CRC-checked, optionally-compressed record file.
//
// TPU-native re-implementation of the reference's C++ recordio
// (paddle/fluid/recordio/{chunk,writer,scanner}.h): same layout ideas —
// records are batched into chunks, each chunk carries a header with a
// magic number, compressor id, record count, payload length and CRC32 —
// exposed here through a flat C ABI so Python binds via ctypes (no
// pybind11 in the image).
//
// Layout per chunk:
//   u32 magic (0x0dea11ed)  u32 compressor (0=raw, 1=zlib)
//   u32 num_records         u32 payload_len (compressed)
//   u32 raw_len             u32 crc32(payload)
//   payload: concat of (u32 len, bytes) per record, possibly deflated.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
#include <zlib.h>

#include <condition_variable>
#include <mutex>
#include <queue>
#include <thread>

namespace {

constexpr uint32_t kMagic = 0x0dea11ed;
constexpr uint32_t kRaw = 0;
constexpr uint32_t kZlib = 1;

struct Header {
  uint32_t magic, compressor, num_records, payload_len, raw_len, crc;
};

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

std::vector<uint8_t> deflate_buf(const std::vector<uint8_t>& in) {
  uLongf out_len = compressBound(in.size());
  std::vector<uint8_t> out(out_len);
  if (compress2(out.data(), &out_len, in.data(), in.size(), 6) != Z_OK)
    return {};
  out.resize(out_len);
  return out;
}

bool inflate_buf(const uint8_t* in, size_t in_len, std::vector<uint8_t>* out,
                 size_t raw_len) {
  out->resize(raw_len);
  uLongf dst = raw_len;
  if (uncompress(out->data(), &dst, in, in_len) != Z_OK) return false;
  out->resize(dst);
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------------

struct RecWriter {
  FILE* f = nullptr;
  uint32_t compressor = kZlib;
  uint32_t max_records = 1000;
  std::vector<uint8_t> buf;
  uint32_t n_records = 0;

  bool flush_chunk() {
    if (n_records == 0) return true;
    std::vector<uint8_t> payload;
    uint32_t comp = compressor;
    if (compressor == kZlib) {
      payload = deflate_buf(buf);
      if (payload.empty() && !buf.empty()) return false;
    } else {
      payload = buf;
    }
    Header h{kMagic, comp, n_records, (uint32_t)payload.size(),
             (uint32_t)buf.size(),
             (uint32_t)crc32(0, payload.data(), payload.size())};
    if (!write_all(f, &h, sizeof(h))) return false;
    if (!write_all(f, payload.data(), payload.size())) return false;
    buf.clear();
    n_records = 0;
    return true;
  }
};

extern "C" {

RecWriter* recio_writer_open(const char* path, uint32_t compressor,
                             uint32_t max_records_per_chunk) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new RecWriter();
  w->f = f;
  w->compressor = compressor;
  if (max_records_per_chunk) w->max_records = max_records_per_chunk;
  return w;
}

int recio_writer_write(RecWriter* w, const uint8_t* data, uint32_t len) {
  uint32_t n = len;
  const uint8_t* np = reinterpret_cast<const uint8_t*>(&n);
  w->buf.insert(w->buf.end(), np, np + 4);
  w->buf.insert(w->buf.end(), data, data + len);
  w->n_records++;
  if (w->n_records >= w->max_records) return w->flush_chunk() ? 0 : -1;
  return 0;
}

int recio_writer_close(RecWriter* w) {
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------------------
// Scanner (sequential; chunk index enables seeking/sharding)
// ---------------------------------------------------------------------------

struct RecScanner {
  FILE* f = nullptr;
  std::vector<uint8_t> chunk;          // decoded records of current chunk
  size_t pos = 0;                      // cursor within chunk
  std::vector<uint8_t> record;         // last record returned

  bool next_chunk() {
    Header h;
    if (fread(&h, 1, sizeof(h), f) != sizeof(h)) return false;
    if (h.magic != kMagic) return false;
    std::vector<uint8_t> payload(h.payload_len);
    if (fread(payload.data(), 1, h.payload_len, f) != h.payload_len)
      return false;
    if ((uint32_t)crc32(0, payload.data(), payload.size()) != h.crc)
      return false;
    if (h.compressor == kZlib) {
      if (!inflate_buf(payload.data(), payload.size(), &chunk, h.raw_len))
        return false;
    } else {
      chunk = std::move(payload);
    }
    pos = 0;
    return true;
  }
};

RecScanner* recio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new RecScanner();
  s->f = f;
  return s;
}

// returns 1 on success, 0 on EOF, -1 on corruption; *len_out = record size
int recio_scanner_next(RecScanner* s, const uint8_t** out,
                       uint32_t* len_out) {
  if (s->pos >= s->chunk.size()) {
    if (!s->next_chunk()) {
      if (feof(s->f)) return 0;
      return -1;
    }
  }
  if (s->pos + 4 > s->chunk.size()) return -1;
  uint32_t len;
  memcpy(&len, s->chunk.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + len > s->chunk.size()) return -1;
  s->record.assign(s->chunk.begin() + s->pos,
                   s->chunk.begin() + s->pos + len);
  s->pos += len;
  *out = s->record.data();
  *len_out = len;
  return 1;
}

void recio_scanner_close(RecScanner* s) {
  fclose(s->f);
  delete s;
}

// ---------------------------------------------------------------------------
// Threaded prefetching loader: N reader threads fan records into a
// bounded queue (the native analog of the reference's double-buffered /
// threaded reader ops, operators/reader/create_double_buffer_reader_op.cc)
// ---------------------------------------------------------------------------

struct Loader {
  std::vector<std::string> files;
  std::queue<std::vector<uint8_t>> q;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  size_t capacity = 256;
  bool done = false;
  bool stop = false;
  std::vector<std::thread> threads;
  std::vector<uint8_t> record;
  size_t active = 0;

  void run(size_t shard, size_t n_shards) {
    for (size_t i = shard; i < files.size(); i += n_shards) {
      RecScanner* s = recio_scanner_open(files[i].c_str());
      if (!s) continue;
      const uint8_t* p;
      uint32_t len;
      while (recio_scanner_next(s, &p, &len) == 1) {
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return q.size() < capacity || stop; });
        if (stop) { recio_scanner_close(s); goto out; }
        q.emplace(p, p + len);
        cv_pop.notify_one();
      }
      recio_scanner_close(s);
    }
  out:
    std::unique_lock<std::mutex> lk(mu);
    if (--active == 0) { done = true; cv_pop.notify_all(); }
  }
};

Loader* recio_loader_open(const char** paths, uint32_t n_files,
                          uint32_t n_threads, uint32_t capacity) {
  auto* l = new Loader();
  for (uint32_t i = 0; i < n_files; i++) l->files.emplace_back(paths[i]);
  if (capacity) l->capacity = capacity;
  uint32_t nt = n_threads ? n_threads : 1;
  if (nt > l->files.size()) nt = l->files.size() ? l->files.size() : 1;
  l->active = nt;
  for (uint32_t t = 0; t < nt; t++)
    l->threads.emplace_back(&Loader::run, l, t, nt);
  return l;
}

int recio_loader_next(Loader* l, const uint8_t** out, uint32_t* len_out) {
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_pop.wait(lk, [&] { return !l->q.empty() || l->done; });
  if (l->q.empty()) return 0;
  l->record = std::move(l->q.front());
  l->q.pop();
  l->cv_push.notify_one();
  *out = l->record.data();
  *len_out = (uint32_t)l->record.size();
  return 1;
}

void recio_loader_close(Loader* l) {
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->stop = true;
    l->cv_push.notify_all();
  }
  for (auto& t : l->threads) t.join();
  delete l;
}

}  // extern "C"
