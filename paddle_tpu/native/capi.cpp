// Embeddable pure-C inference ABI — the reference capi analog
// (paddle/capi/capi.h: paddle_gradient_machine_create_for_inference /
// _forward; here pd_tpu_create / pd_tpu_run).
//
// The shell is native C++; inference executes through the framework's
// XLA/PJRT path by embedding CPython (the reference embeds CPython the
// same way in its data layer, gserver/dataproviders/PyDataProvider2.cpp).
// A C host links this library, calls pd_tpu_init() once, then
// create/run/destroy — no Python in the host's source.
//
// Build: g++ -O2 -shared -fPIC capi.cpp $(python3-config --includes)
//        $(python3-config --ldflags --embed) -o libpaddletpu_capi.so

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Predictor {
  PyObject* obj;            // paddle_tpu.serving.Predictor
  std::vector<std::string> feed_names;
};

struct RunResult {
  std::vector<std::string> payloads;            // raw bytes per output
  std::vector<std::vector<long long>> shapes;
  std::vector<std::string> dtypes;
};

PyObject* serving_module() {
  return PyImport_ImportModule("paddle_tpu.serving");
}

}  // namespace

extern "C" {

// Initialize the embedded interpreter (no-op when hosted inside an
// already-running Python, e.g. when loaded via ctypes).  Returns 0 on ok.
int pd_tpu_init() {
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  PyGILState_STATE g = PyGILState_Ensure();
  PyObject* m = serving_module();
  int rc = 0;
  if (m == nullptr) {
    set_error_from_python();
    rc = 1;
  } else {
    Py_DECREF(m);
  }
  PyGILState_Release(g);
  if (we_initialized) {
    // Py_InitializeEx leaves THIS thread holding the GIL; release it so
    // any host thread can PyGILState_Ensure in pd_tpu_create/run (the
    // saved thread state is intentionally kept for the process lifetime).
    (void)PyEval_SaveThread();
  }
  return rc;
}

const char* pd_tpu_last_error() { return g_last_error.c_str(); }

// Load a saved inference model directory; returns a handle or NULL.
void* pd_tpu_create(const char* model_dir) {
  PyGILState_STATE g = PyGILState_Ensure();
  Predictor* p = nullptr;
  PyObject* m = serving_module();
  if (m != nullptr) {
    PyObject* obj = PyObject_CallMethod(m, "_capi_create", "s", model_dir);
    if (obj != nullptr) {
      PyObject* names =
          PyObject_CallMethod(m, "_capi_feed_names", "O", obj);
      if (names != nullptr) {
        p = new Predictor();
        p->obj = obj;
        Py_ssize_t n = PyList_Size(names);
        for (Py_ssize_t i = 0; i < n; ++i) {
          p->feed_names.emplace_back(
              PyUnicode_AsUTF8(PyList_GetItem(names, i)));
        }
        Py_DECREF(names);
      } else {
        set_error_from_python();
        Py_DECREF(obj);
      }
    } else {
      set_error_from_python();
    }
    Py_DECREF(m);
  } else {
    set_error_from_python();
  }
  PyGILState_Release(g);
  return p;
}

int pd_tpu_num_feeds(void* handle) {
  return static_cast<int>(static_cast<Predictor*>(handle)->feed_names.size());
}

const char* pd_tpu_feed_name(void* handle, int i) {
  Predictor* p = static_cast<Predictor*>(handle);
  if (i < 0 || i >= static_cast<int>(p->feed_names.size())) return nullptr;
  return p->feed_names[i].c_str();
}

// Run inference.
//   n_feeds inputs: name / raw data / byte length / shape (rank dims) /
//   dtype string ("float32", "int64", ...).
// Returns an opaque result handle (NULL on error); outputs are read back
// with the pd_tpu_result_* accessors and freed with pd_tpu_free_result.
void* pd_tpu_run(void* handle, int n_feeds, const char** names,
                 const void** data, const long long* byte_lens,
                 const long long* const* shapes, const int* ranks,
                 const char** dtypes) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE g = PyGILState_Ensure();
  RunResult* result = nullptr;
  PyObject *m = nullptr, *py_names = nullptr, *bufs = nullptr,
           *py_shapes = nullptr, *py_dtypes = nullptr, *ret = nullptr;
  m = serving_module();
  if (m == nullptr) goto fail;
  py_names = PyList_New(n_feeds);
  bufs = PyList_New(n_feeds);
  py_shapes = PyList_New(n_feeds);
  py_dtypes = PyList_New(n_feeds);
  for (int i = 0; i < n_feeds; ++i) {
    PyList_SetItem(py_names, i, PyUnicode_FromString(names[i]));
    PyList_SetItem(
        bufs, i,
        PyMemoryView_FromMemory(
            const_cast<char*>(static_cast<const char*>(data[i])),
            static_cast<Py_ssize_t>(byte_lens[i]), PyBUF_READ));
    PyObject* shp = PyTuple_New(ranks[i]);
    for (int d = 0; d < ranks[i]; ++d) {
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(shapes[i][d]));
    }
    PyList_SetItem(py_shapes, i, shp);
    PyList_SetItem(py_dtypes, i, PyUnicode_FromString(dtypes[i]));
  }
  ret = PyObject_CallMethod(m, "_capi_run", "OOOOO", p->obj, py_names, bufs,
                            py_shapes, py_dtypes);
  if (ret == nullptr) goto fail;
  {
    PyObject* payloads = PyTuple_GetItem(ret, 0);
    PyObject* oshapes = PyTuple_GetItem(ret, 1);
    PyObject* odtypes = PyTuple_GetItem(ret, 2);
    result = new RunResult();
    Py_ssize_t n = PyList_Size(payloads);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* b = PyList_GetItem(payloads, i);
      char* raw;
      Py_ssize_t len;
      PyBytes_AsStringAndSize(b, &raw, &len);
      result->payloads.emplace_back(raw, static_cast<size_t>(len));
      PyObject* shp = PyList_GetItem(oshapes, i);
      std::vector<long long> dims;
      for (Py_ssize_t d = 0; d < PyTuple_Size(shp); ++d) {
        dims.push_back(PyLong_AsLongLong(PyTuple_GetItem(shp, d)));
      }
      result->shapes.push_back(dims);
      result->dtypes.emplace_back(
          PyUnicode_AsUTF8(PyList_GetItem(odtypes, i)));
    }
  }
  goto done;
fail:
  set_error_from_python();
done:
  Py_XDECREF(ret);
  Py_XDECREF(py_dtypes);
  Py_XDECREF(py_shapes);
  Py_XDECREF(bufs);
  Py_XDECREF(py_names);
  Py_XDECREF(m);
  PyGILState_Release(g);
  return result;
}

int pd_tpu_result_count(void* result) {
  return static_cast<int>(static_cast<RunResult*>(result)->payloads.size());
}

const void* pd_tpu_result_data(void* result, int i, long long* byte_len) {
  RunResult* r = static_cast<RunResult*>(result);
  *byte_len = static_cast<long long>(r->payloads[i].size());
  return r->payloads[i].data();
}

int pd_tpu_result_rank(void* result, int i) {
  return static_cast<int>(static_cast<RunResult*>(result)->shapes[i].size());
}

long long pd_tpu_result_dim(void* result, int i, int d) {
  return static_cast<RunResult*>(result)->shapes[i][d];
}

const char* pd_tpu_result_dtype(void* result, int i) {
  return static_cast<RunResult*>(result)->dtypes[i].c_str();
}

void pd_tpu_free_result(void* result) {
  delete static_cast<RunResult*>(result);
}

void pd_tpu_destroy(void* handle) {
  Predictor* p = static_cast<Predictor*>(handle);
  PyGILState_STATE g = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(g);
  delete p;
}

}  // extern "C"
