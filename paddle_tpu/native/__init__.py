"""Native (C++) runtime components, bound via ctypes.

The reference implements its IO/runtime layer in C++ (recordio at
``paddle/fluid/recordio/``, threaded readers under
``paddle/fluid/operators/reader/``); this package keeps that split: the
compute path is XLA, the data path is native code.  The shared library is
built on first use with g++ (no pybind11 in the image — flat C ABI +
ctypes) and cached next to the sources.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "recordio.cpp")
_LIB = os.path.join(_DIR, "libpaddletpu_native.so")

_lock = threading.Lock()
_lib = None
_build_error = None


def _build():
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", _LIB, "-lz", "-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Build (if needed) and load the native library; returns None when a
    toolchain is unavailable (callers fall back to pure Python)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if (not os.path.exists(_LIB) or
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB)
        except Exception as e:  # pragma: no cover - toolchain missing
            _build_error = e
            return None
        lib.recio_writer_open.restype = ctypes.c_void_p
        lib.recio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                          ctypes.c_uint32]
        lib.recio_writer_write.restype = ctypes.c_int
        lib.recio_writer_write.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_uint32]
        lib.recio_writer_close.restype = ctypes.c_int
        lib.recio_writer_close.argtypes = [ctypes.c_void_p]
        lib.recio_scanner_open.restype = ctypes.c_void_p
        lib.recio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.recio_scanner_next.restype = ctypes.c_int
        lib.recio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.recio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.recio_loader_open.restype = ctypes.c_void_p
        lib.recio_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
            ctypes.c_uint32, ctypes.c_uint32]
        lib.recio_loader_next.restype = ctypes.c_int
        lib.recio_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.recio_loader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib
