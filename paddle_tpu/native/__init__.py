"""Native (C++) runtime components, bound via ctypes.

The reference implements its IO/runtime layer in C++ (recordio at
``paddle/fluid/recordio/``, threaded readers under
``paddle/fluid/operators/reader/``); this package keeps that split: the
compute path is XLA, the data path is native code.  The shared library is
built on first use with g++ (no pybind11 in the image — flat C ABI +
ctypes) and cached next to the sources.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "recordio.cpp")
_LIB = os.path.join(_DIR, "libpaddletpu_native.so")

_lock = threading.Lock()
_lib = None
_build_error = None


def _build():
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", _LIB, "-lz", "-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Build (if needed) and load the native library; returns None when a
    toolchain is unavailable (callers fall back to pure Python)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if (not os.path.exists(_LIB) or
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB)
        except Exception as e:  # pragma: no cover - toolchain missing
            _build_error = e
            return None
        lib.recio_writer_open.restype = ctypes.c_void_p
        lib.recio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                          ctypes.c_uint32]
        lib.recio_writer_write.restype = ctypes.c_int
        lib.recio_writer_write.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p,
                                           ctypes.c_uint32]
        lib.recio_writer_close.restype = ctypes.c_int
        lib.recio_writer_close.argtypes = [ctypes.c_void_p]
        lib.recio_scanner_open.restype = ctypes.c_void_p
        lib.recio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.recio_scanner_next.restype = ctypes.c_int
        lib.recio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.recio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.recio_loader_open.restype = ctypes.c_void_p
        lib.recio_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
            ctypes.c_uint32, ctypes.c_uint32]
        lib.recio_loader_next.restype = ctypes.c_int
        lib.recio_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.recio_loader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# capi: the embeddable C inference ABI (capi.cpp) — built separately since
# it links against libpython.
# ---------------------------------------------------------------------------

_CAPI_SRC = os.path.join(_DIR, "capi.cpp")
_CAPI_LIB = os.path.join(_DIR, "libpaddletpu_capi.so")
_capi_lib = None
_capi_error = None


def _python_flags():
    import sysconfig
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    return [f"-I{inc}"], [f"-L{libdir}", f"-lpython{ver}"]


def load_capi():
    """Build (if needed) and load the C inference ABI; None if no
    toolchain."""
    global _capi_lib, _capi_error
    with _lock:
        if _capi_lib is not None or _capi_error is not None:
            return _capi_lib
        try:
            if (not os.path.exists(_CAPI_LIB) or
                    os.path.getmtime(_CAPI_LIB) <
                    os.path.getmtime(_CAPI_SRC)):
                incs, libs = _python_flags()
                cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
                       + incs + [_CAPI_SRC, "-o", _CAPI_LIB] + libs)
                subprocess.run(cmd, check=True, capture_output=True)
            lib = ctypes.CDLL(_CAPI_LIB, mode=ctypes.RTLD_GLOBAL)
        except Exception as e:  # pragma: no cover - toolchain missing
            _capi_error = e
            return None
        lib.pd_tpu_init.restype = ctypes.c_int
        lib.pd_tpu_last_error.restype = ctypes.c_char_p
        lib.pd_tpu_create.restype = ctypes.c_void_p
        lib.pd_tpu_create.argtypes = [ctypes.c_char_p]
        lib.pd_tpu_num_feeds.restype = ctypes.c_int
        lib.pd_tpu_num_feeds.argtypes = [ctypes.c_void_p]
        lib.pd_tpu_feed_name.restype = ctypes.c_char_p
        lib.pd_tpu_feed_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pd_tpu_run.restype = ctypes.c_void_p
        lib.pd_tpu_run.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.pd_tpu_result_count.restype = ctypes.c_int
        lib.pd_tpu_result_count.argtypes = [ctypes.c_void_p]
        lib.pd_tpu_result_data.restype = ctypes.c_void_p
        lib.pd_tpu_result_data.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong)]
        lib.pd_tpu_result_rank.restype = ctypes.c_int
        lib.pd_tpu_result_rank.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pd_tpu_result_dim.restype = ctypes.c_longlong
        lib.pd_tpu_result_dim.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_int]
        lib.pd_tpu_result_dtype.restype = ctypes.c_char_p
        lib.pd_tpu_result_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pd_tpu_free_result.argtypes = [ctypes.c_void_p]
        lib.pd_tpu_destroy.argtypes = [ctypes.c_void_p]
        _capi_lib = lib
        return _capi_lib
