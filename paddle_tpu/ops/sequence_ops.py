"""Sequence (LoD) ops — the reference's distinctive ragged-tensor workload
(``paddle/fluid/operators/sequence_*_op.cc``, ``operators/math/sequence*``).

TPU re-design: LoD row-splits are STATIC trace-time metadata (they ride the
jit cache key, see ``executor._get_compiled``), so every lowering here can
build gather/segment index tables in numpy at trace time and emit dense XLA
ops — no dynamic shapes.  Variable-length batches should be bucketed
upstream (reader decorators) to bound recompilation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


def _infer_ragged(op, block):
    """Out is ragged: row count unknown at build time, features preserved."""
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    out.shape = (-1,) + tuple(x.shape[1:])
    out.dtype = x.dtype
    out.lod_level = x.lod_level


def _infer_seq_conv(op, block):
    x = block.var(op.input("X")[0])
    filt = block.var(op.input("Filter")[0])
    out = block.var(op.output("Out")[0])
    out.shape = (-1 if x.shape is None else x.shape[0], filt.shape[1])
    out.dtype = x.dtype
    out.lod_level = x.lod_level


def _lengths(lod, level=0):
    splits = lod[level]
    return [int(splits[i + 1] - splits[i]) for i in range(len(splits) - 1)]


def _segment_ids(lod, level=0):
    """Flat [N] -> sequence index, as a static numpy array."""
    out = []
    for i, L in enumerate(_lengths(lod, level)):
        out.extend([i] * L)
    return np.asarray(out, dtype=np.int32)


def _last_level(lod):
    # DynLoD raises its own unsupported-op error on len()
    return len(lod) - 1


def _require_lod(ctx, slot="X"):
    lod = ctx.input_lod(slot)
    if lod is None:
        x = ctx.input(slot)
        # dense fallback (reference semantics for lod_level=0 feeds): each
        # row is its own length-1 sequence
        if x.ndim >= 1:
            return [list(range(x.shape[0] + 1))]
        raise ValueError(
            f"op {ctx.op.type} requires LoD metadata on input {slot!r}")
    return lod


def _is_dyn(lod):
    from paddle_tpu.lod import DynLoD
    # _ConstSplits presents a static lod through the same runtime-splits
    # interface (compiled blocks in bucketed programs)
    return isinstance(lod, (DynLoD, _ConstSplits))


def _segment_tables(ctx, lod, n_rows):
    """(seg [N] int32, lengths [B] jnp, num_segments, splits [B+1] jnp,
    valid [N] bool|None) — from a static lod (trace-time numpy) or a
    DynLoD (runtime row-splits, bucketed mode — lod.py).  Padding rows get
    segment id == num_segments, which jax segment ops DROP."""
    if _is_dyn(lod):
        splits = lod.splits(ctx.env).astype(jnp.int32)
        num = lod.num_seqs
        lengths = splits[1:] - splits[:-1]
        rows = jnp.arange(n_rows)
        seg = jnp.searchsorted(splits[1:], rows,
                               side="right").astype(jnp.int32)
        valid = rows < splits[-1]
        seg = jnp.where(valid, seg, num)
        return seg, lengths, num, splits, valid
    level = _last_level(lod)
    seg = jnp.asarray(_segment_ids(lod, level))
    lengths_np = np.asarray(_lengths(lod, level))
    splits = jnp.asarray(np.asarray(lod[level], dtype=np.int32))
    return seg, jnp.asarray(lengths_np), len(lengths_np), splits, None


# ---------------------------------------------------------------------------
# sequence_pool (sum/average/max/min/last/first/sqrt)
# ---------------------------------------------------------------------------

@register_op("sequence_pool", infer_shape=_infer_ragged)
def sequence_pool_lower(ctx: LowerContext):
    x = ctx.input("X")                      # [N, D]
    lod = _require_lod(ctx)
    pooltype = ctx.attr("pooltype", "AVERAGE").upper()
    seg, lengths, num, splits, _ = _segment_tables(ctx, lod, x.shape[0])
    denom_shape = (-1,) + (1,) * (x.ndim - 1)

    if pooltype == "SUM":
        out = jax.ops.segment_sum(x, seg, num_segments=num)
    elif pooltype in ("AVERAGE", "MEAN"):
        s = jax.ops.segment_sum(x, seg, num_segments=num)
        out = s / jnp.maximum(lengths, 1).astype(x.dtype).reshape(
            denom_shape)
    elif pooltype == "SQRT":
        s = jax.ops.segment_sum(x, seg, num_segments=num)
        out = s / jnp.sqrt(jnp.maximum(lengths, 1).astype(x.dtype)).reshape(
            denom_shape)
    elif pooltype == "MAX":
        out = jax.ops.segment_max(x, seg, num_segments=num)
        # MaxIndex = per-(segment, feature) argmax row (first match), as
        # the reference MaxSeqPoolFunctor stores (math/sequence_pooling.cc)
        N = x.shape[0]
        rows = jnp.arange(N).reshape(-1, *([1] * (x.ndim - 1)))
        safe_seg = jnp.minimum(seg, num - 1)  # padding rows: any gather
        is_max = (x == out[safe_seg]) & (seg < num).reshape(
            (-1,) + (1,) * (x.ndim - 1))
        idx = jax.ops.segment_min(
            jnp.where(is_max, rows, N), seg, num_segments=num)
        ctx.set_output("MaxIndex", idx)
    elif pooltype == "MIN":
        out = jax.ops.segment_min(x, seg, num_segments=num)
    elif pooltype == "LAST":
        out = x[splits[1:] - 1]
    elif pooltype == "FIRST":
        out = x[splits[:-1]]
    else:
        raise NotImplementedError(f"sequence_pool type {pooltype}")
    ctx.set_output("Out", out)
    if not _is_dyn(lod) and _last_level(lod) > 0:
        level = _last_level(lod)
        ctx.set_output_lod("Out", [list(lod[i]) for i in range(level)])


# ---------------------------------------------------------------------------
# sequence_softmax
# ---------------------------------------------------------------------------

@register_op("sequence_softmax", infer_shape=_infer_ragged)
def sequence_softmax_lower(ctx: LowerContext):
    x = ctx.input("X")          # [N] or [N, 1]
    lod = _require_lod(ctx)
    flat = x.reshape(-1)
    seg, _, num, _, valid = _segment_tables(ctx, lod, flat.shape[0])
    safe_seg = jnp.minimum(seg, num - 1)
    mx = jax.ops.segment_max(flat, seg, num_segments=num)
    e = jnp.exp(flat - mx[safe_seg])
    if valid is not None:
        e = jnp.where(valid, e, 0.0)
    denom = jax.ops.segment_sum(e, seg, num_segments=num)
    out = (e / jnp.maximum(denom[safe_seg], 1e-30)).reshape(x.shape)
    ctx.set_output("Out", out)
    if _is_dyn(lod):
        ctx.set_output_lod("Out", lod)
    else:
        ctx.set_output_lod("Out", [list(l) for l in lod])


# ---------------------------------------------------------------------------
# sequence_expand: repeat x rows to match y's lod
# ---------------------------------------------------------------------------

@register_op("sequence_expand", infer_shape=_infer_ragged)
def sequence_expand_lower(ctx: LowerContext):
    x = ctx.input("X")
    x_lod = ctx.input_lod("X")
    y_lod = _require_lod(ctx, "Y")
    if _is_dyn(y_lod):
        if x_lod is None:
            # dense-x case (the attention-context pattern: one row per
            # sequence broadcast back over its tokens)
            y_arr = ctx.input("Y")
            n = y_arr.shape[0]
            seg, _, num, _, valid = _segment_tables(ctx, y_lod, n)
            safe = jnp.minimum(seg, num - 1)
            out = jnp.where(valid[(...,) + (None,) * (x.ndim - 1)],
                            x[safe], 0)
            ctx.set_output("Out", out)
            ctx.set_output_lod("Out", y_lod)
            return
        # ragged-x expansion (the beam-expansion pattern: repeat each x
        # sub-sequence r_i times, r_i from y's lod).  Static-shape
        # dialect: output rows are bounded by n_x_rows * rep_cap and the
        # output lod gets B * rep_cap sequence slots — slot (i, k) is
        # seq i's k-th repeat, EMPTY (zero length) when k >= r_i, so the
        # real rows stay contiguous and in reference order; only the
        # sequence table carries padding entries.
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        if _is_dyn(x_lod):
            x_splits = x_lod.splits(ctx.env).astype(jnp.int32)
            bx = x_lod.num_seqs
            x_cap = x_lod.maxlen_bucket
        else:
            x_splits = jnp.asarray(np.asarray(x_lod[0], np.int32))
            bx = len(x_lod[0]) - 1
            x_cap = int(max(np.diff(np.asarray(x_lod[0])), default=0))
        y_splits = y_lod.splits(ctx.env).astype(jnp.int32)
        rep_cap = y_lod.maxlen_bucket
        if y_lod.num_seqs != bx:
            raise ValueError(
                f"sequence_expand: X has {bx} sequences but Y has "
                f"{y_lod.num_seqs}")
        len_x = x_splits[1:] - x_splits[:-1]          # [B]
        rep = y_splits[1:] - y_splits[:-1]            # [B]
        n_slots = bx * rep_cap
        slot_i = jnp.arange(n_slots) // rep_cap
        slot_k = jnp.arange(n_slots) % rep_cap
        slot_len = jnp.where(slot_k < rep[slot_i], len_x[slot_i], 0)
        out_splits = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(slot_len).astype(jnp.int32)])
        n_out = int(x.shape[0]) * rep_cap
        r = jnp.arange(n_out)
        slot = jnp.clip(jnp.searchsorted(out_splits[1:], r, side="right")
                        .astype(jnp.int32), 0, n_slots - 1)
        t = r - out_splits[slot]
        src = jnp.clip(x_splits[slot // rep_cap] + t, 0, x.shape[0] - 1)
        valid = (r < out_splits[-1]).reshape(
            (-1,) + (1,) * (x.ndim - 1))
        out = jnp.where(valid, x[src], 0)
        name = ctx.op.output("Out")[0] + SPLITS_SUFFIX
        ctx.outputs[name] = out_splits
        ctx.set_output("Out", out)
        ctx.set_output_lod("Out", DynLoD(name, n_slots, x_cap))
        return
    ref_level = ctx.attr("ref_level", -1)
    if ref_level == -1:
        ref_level = len(y_lod) - 1
    rep = _lengths(y_lod, ref_level)
    if x_lod is None:
        # each x row i repeats rep[i] times
        idx = np.repeat(np.arange(len(rep)), rep).astype(np.int32)
        out = x[jnp.asarray(idx)]
        out_lod = None
    else:
        # expand whole x sub-sequences
        xs = np.asarray(x_lod[0])
        idx = []
        new_splits = [0]
        for i, r in enumerate(rep):
            seq = list(range(xs[i], xs[i + 1]))
            for _ in range(max(r, 1) if r else 0):
                idx.extend(seq)
                new_splits.append(new_splits[-1] + len(seq))
        out = x[jnp.asarray(np.asarray(idx, dtype=np.int32))]
        out_lod = [new_splits]
    ctx.set_output("Out", out)
    if out_lod is not None:
        ctx.set_output_lod("Out", out_lod)
    else:
        ctx.set_output_lod("Out", [list(y_lod[ref_level])])


# ---------------------------------------------------------------------------
# sequence_concat / sequence_reshape / sequence_slice / sequence_erase
# ---------------------------------------------------------------------------

@register_op("sequence_concat", infer_shape=_infer_ragged)
def sequence_concat_lower(ctx: LowerContext):
    xs = ctx.inputs("X")
    names = ctx.op.input("X")
    lods = [ctx.var_lod(n) for n in names]
    if any(l is None for l in lods):
        ctx.set_output("Out", jnp.concatenate(xs, axis=0))
        return
    if any(_is_dyn(l) for l in lods):
        # bucketed mode: interleave per-sequence with a RUNTIME gather
        # table — out seq i = concat_k (input k's seq i); K is static so
        # the per-input membership test unrolls into where-chains.
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        num = next(l for l in lods if _is_dyn(l)).num_seqs
        n_out = sum(int(x.shape[0]) for x in xs)
        offsets = np.cumsum([0] + [int(x.shape[0]) for x in xs])
        splits_k, lengths_k = [], []
        for k, l in enumerate(lods):
            if _is_dyn(l):
                sp = l.splits(ctx.env).astype(jnp.int32)
            else:
                sp = jnp.asarray(np.asarray(l[0], np.int32))
            splits_k.append(sp)
            lengths_k.append(sp[1:] - sp[:-1])
        out_lengths = sum(lengths_k)
        out_splits = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(out_lengths).astype(jnp.int32)])
        r = jnp.arange(n_out)
        seg = jnp.searchsorted(out_splits[1:], r,
                               side="right").astype(jnp.int32)
        segc = jnp.clip(seg, 0, num - 1)
        pos = r - out_splits[segc]
        valid = r < out_splits[-1]
        src = jnp.zeros(n_out, jnp.int32)
        found = jnp.zeros(n_out, bool)
        acc = jnp.zeros(n_out, jnp.int32)
        for k in range(len(xs)):
            lk = lengths_k[k][segc]
            in_k = (pos >= acc) & (pos < acc + lk)
            src_k = offsets[k] + splits_k[k][segc] + (pos - acc)
            src = jnp.where(in_k & ~found, src_k, src)
            found = found | in_k
            acc = acc + lk
        allx = jnp.concatenate(xs, axis=0)
        gathered = allx[jnp.clip(src, 0, n_out - 1)]
        mask = valid.reshape((-1,) + (1,) * (gathered.ndim - 1))
        out = jnp.where(mask, gathered, 0)
        name = ctx.op.output("Out")[0] + SPLITS_SUFFIX
        ctx.outputs[name] = out_splits
        ctx.set_output("Out", out)
        # per-input longest-sequence bound: dyn inputs ride their bucket,
        # static inputs their actual max length (NOT the combined row
        # count — maxlen_bucket is the while_loop trip bound downstream)
        maxlen = sum(
            l.maxlen_bucket if _is_dyn(l)
            else int(max(np.diff(np.asarray(l[0])), default=0))
            for l in lods)
        ctx.set_output_lod("Out", DynLoD(name, num, maxlen))
        return
    # interleave per-sequence: out seq i = concat of each input's seq i
    splits = [np.asarray(l[0]) for l in lods]
    n_seq = len(splits[0]) - 1
    parts, new_splits = [], [0]
    order = []
    base = 0
    offsets = np.cumsum([0] + [x.shape[0] for x in xs])
    for i in range(n_seq):
        total = 0
        for k, sp in enumerate(splits):
            order.extend(range(offsets[k] + sp[i], offsets[k] + sp[i + 1]))
            total += int(sp[i + 1] - sp[i])
        new_splits.append(new_splits[-1] + total)
    allx = jnp.concatenate(xs, axis=0)
    ctx.set_output("Out", allx[jnp.asarray(np.asarray(order, np.int32))])
    ctx.set_output_lod("Out", [new_splits])


@register_op("sequence_reverse", infer_shape=_infer_ragged)
def sequence_reverse_lower(ctx: LowerContext):
    """Reverse rows within each sequence (reference
    ``sequence_reverse_op.h``; used by the legacy DSL's
    ``recurrent_group(reverse=True)``).  LoD splits are unchanged; the
    gather index table is built at trace time, so gradients flow through
    the (constant-index) gather."""
    x = ctx.input("X")
    lod = ctx.var_lod(ctx.op.input("X")[0])
    if lod is None:
        ctx.set_output("Out", x[::-1])
        return
    splits = np.asarray(lod[0])
    order = []
    for i in range(len(splits) - 1):
        order.extend(range(int(splits[i + 1]) - 1, int(splits[i]) - 1, -1))
    ctx.set_output("Out", x[jnp.asarray(np.asarray(order, np.int32))])
    ctx.set_output_lod("Out", [list(map(int, splits))])


def _infer_seq_reshape(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = (-1, op.attr("new_dim"))
    out.dtype = x.dtype
    out.lod_level = x.lod_level


@register_op("sequence_reshape", infer_shape=_infer_seq_reshape)
def sequence_reshape_lower(ctx: LowerContext):
    x = ctx.input("X")
    lod = _require_lod(ctx)
    new_dim = ctx.attr("new_dim")
    out = x.reshape(-1, new_dim)
    if _is_dyn(lod):
        # runtime splits scale by the same static ratio; padding rows
        # stay at the tail (zeros reshaped are zeros)
        from paddle_tpu.lod import DynLoD
        ratio_num, ratio_den = x.shape[1], new_dim
        splits = lod.splits(ctx.env) * ratio_num // ratio_den
        scaled_name = ctx.op.output("Out")[0] + "@lod0"
        ctx.outputs[scaled_name] = splits.astype(jnp.int32)
        ctx.set_output_lod(
            "Out", DynLoD(scaled_name, lod.num_seqs,
                          lod.maxlen_bucket * ratio_num // ratio_den))
        ctx.set_output("Out", out)
        return
    ratio = x.shape[1] / new_dim
    splits = [int(s * ratio) for s in lod[0]]
    ctx.set_output("Out", out)
    ctx.set_output_lod("Out", [splits])


class _ConstSplits:
    """Adapter: a STATIC lod presented through the DynLoD interface
    (constant splits tensor) — used when a host-op's bucketed branch must
    run traced but the variable's lod is static (mixed programs under
    ``lod_buckets``, where the block compiles as a whole)."""

    def __init__(self, level_splits):
        arr = np.asarray(level_splits, np.int32)
        self._splits = jnp.asarray(arr)
        self.num_seqs = len(arr) - 1
        lengths = np.diff(arr)
        self.maxlen_bucket = int(lengths.max()) if len(lengths) else 0

    def splits(self, env):
        return self._splits


def _is_traced(*vals):
    return any(isinstance(v, jax.core.Tracer) for v in vals
               if v is not None)


@register_op("sequence_slice", infer_shape=_infer_ragged,
             no_gradient=True, host=True, host_dyn_ok=True)
def sequence_slice_lower(ctx: LowerContext):
    x = ctx.input("X")
    lod = _require_lod(ctx)
    if not _is_dyn(lod) and _is_traced(x, ctx.input("Offset")):
        # compiled block (bucketed program) but this var's lod is static:
        # run the traced branch over constant splits
        lod = _ConstSplits(lod[_last_level(lod)])
    if _is_dyn(lod) or isinstance(lod, _ConstSplits):
        # bucketed mode: output stays padded to the input's bucket; rows
        # move via a runtime gather built from the splits tensor
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        offset = ctx.input("Offset").reshape(-1).astype(jnp.int32)
        length = ctx.input("Length").reshape(-1).astype(jnp.int32)
        splits = lod.splits(ctx.env).astype(jnp.int32)
        n = x.shape[0]
        out_splits = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(length).astype(jnp.int32)])
        r = jnp.arange(n)
        seg = jnp.searchsorted(out_splits[1:], r,
                               side="right").astype(jnp.int32)
        segc = jnp.clip(seg, 0, lod.num_seqs - 1)
        valid = r < out_splits[-1]
        src = splits[segc] + offset[segc] + (r - out_splits[segc])
        gathered = x[jnp.clip(src, 0, n - 1)]
        mask = valid.reshape((-1,) + (1,) * (gathered.ndim - 1))
        name = ctx.op.output("Out")[0] + SPLITS_SUFFIX
        ctx.outputs[name] = out_splits
        ctx.set_output("Out", jnp.where(mask, gathered, 0))
        ctx.set_output_lod("Out", DynLoD(name, lod.num_seqs,
                                         lod.maxlen_bucket))
        return
    offset = np.asarray(ctx.input("Offset")).reshape(-1)
    length = np.asarray(ctx.input("Length")).reshape(-1)
    splits = np.asarray(lod[0])
    idx, new_splits = [], [0]
    for i in range(len(splits) - 1):
        start = int(splits[i] + offset[i])
        idx.extend(range(start, start + int(length[i])))
        new_splits.append(new_splits[-1] + int(length[i]))
    ctx.set_output("Out", x[jnp.asarray(np.asarray(idx, np.int32))])
    ctx.set_output_lod("Out", [new_splits])


@register_op("sequence_erase", infer_shape=_infer_ragged,
             no_gradient=True, host=True, host_dyn_ok=True)
def sequence_erase_lower(ctx: LowerContext):
    """Remove tokens in ``tokens`` attr.  Static mode runs at trace time
    on concrete values (data-dependent row count); bucketed mode keeps the
    padded row count and compacts kept rows forward with a stable
    argsort — the new splits ride the runtime lod tensor."""
    x = ctx.input("X")
    tokens = sorted(set(ctx.attr("tokens", [])))
    lod = _require_lod(ctx)
    if not _is_dyn(lod) and _is_traced(x):
        lod = _ConstSplits(lod[_last_level(lod)])
    if _is_dyn(lod):
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        n = x.shape[0]
        seg, _, num, splits, valid = _segment_tables(ctx, lod, n)
        vals = x.reshape(n, -1)[:, 0]
        keep = valid if valid is not None else jnp.ones(n, bool)
        for t in tokens:
            keep = keep & (vals != t)
        # kept rows first, original order (stable); dropped/padding last
        order = jnp.argsort(jnp.logical_not(keep), stable=True)
        kept_count = jnp.sum(keep.astype(jnp.int32))
        gathered = x[order]
        r = jnp.arange(n)
        mask = (r < kept_count).reshape((-1,) + (1,) * (x.ndim - 1))
        # per-sequence kept counts -> new splits
        kept_per_seq = jax.ops.segment_sum(keep.astype(jnp.int32), seg,
                                           num_segments=num + 1)[:num]
        out_splits = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(kept_per_seq).astype(jnp.int32)])
        name = ctx.op.output("Out")[0] + SPLITS_SUFFIX
        ctx.outputs[name] = out_splits
        ctx.set_output("Out", jnp.where(mask, gathered, 0))
        ctx.set_output_lod("Out", DynLoD(name, num, lod.maxlen_bucket))
        return
    vals = np.asarray(x).reshape(-1)
    splits = np.asarray(lod[0])
    keep_vals, new_splits = [], [0]
    tokens = set(tokens)
    for i in range(len(splits) - 1):
        seq = [v for v in vals[splits[i]:splits[i + 1]]
               if int(v) not in tokens]
        keep_vals.extend(seq)
        new_splits.append(new_splits[-1] + len(seq))
    out = jnp.asarray(np.asarray(keep_vals, np.asarray(x).dtype))
    ctx.set_output("Out", out.reshape(-1, *x.shape[1:]) if x.ndim > 1
                   else out)
    ctx.set_output_lod("Out", [new_splits])


@register_op("lod_reset", infer_shape=_infer_ragged)
def lod_reset_lower(ctx: LowerContext):
    x = ctx.input("X")
    x_lod = ctx.var_lod(ctx.op.input("X")[0])
    y_lod = ctx.input_lod("Y") if ctx.op.input("Y") else None
    if _is_dyn(x_lod) or _is_dyn(y_lod):
        # bucketed mode: rows are unchanged; only the splits move.
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        if _is_dyn(y_lod):
            ctx.set_output("Out", x)
            ctx.set_output_lod("Out", y_lod)  # share Y's runtime splits
            return
        target = ctx.attr("target_lod", None)
        if y_lod is not None:                # Y carries a static lod
            splits = jnp.asarray(np.asarray(y_lod[0], np.int32))
            num = len(y_lod[0]) - 1
        elif ctx.op.input("Y"):              # Y holds the splits values
            splits = ctx.input("Y").reshape(-1).astype(jnp.int32)
            num = splits.shape[0] - 1
        elif target is not None:
            splits = jnp.asarray(np.asarray(target, np.int32))
            num = len(target) - 1
        else:
            raise ValueError("lod_reset needs target_lod or Y")
        name = ctx.op.output("Out")[0] + SPLITS_SUFFIX
        ctx.outputs[name] = splits
        ctx.set_output("Out", x)
        ctx.set_output_lod(
            "Out", DynLoD(name, num,
                          x_lod.maxlen_bucket if _is_dyn(x_lod)
                          else x.shape[0]))
        return
    target = ctx.attr("target_lod", None)
    if ctx.op.input("Y"):
        if y_lod is not None:
            target = y_lod[0]
        else:
            target = [int(v) for v in np.asarray(ctx.input("Y")).reshape(-1)]
    ctx.set_output("Out", x)
    ctx.set_output_lod("Out", [list(target)])


# ---------------------------------------------------------------------------
# sequence_conv (context_project + filter matmul)
# ---------------------------------------------------------------------------

def _context_windows(ctx, x, lod, ctx_len, ctx_start):
    """[N, ctx_len*D] sliding-window gather around each token, zero-padded
    at sequence boundaries (reference ``operators/math/context_project.h``).
    Shared by sequence_conv and the raw sequence_context op."""
    N = x.shape[0]
    if _is_dyn(lod):
        # runtime gather table: window slot valid iff the source row stays
        # inside the same sequence (same segment, within valid rows)
        seg, _, num, splits, valid = _segment_tables(ctx, lod, N)
        rows = jnp.arange(N)[:, None]                 # [N, 1]
        src = rows + ctx_start + jnp.arange(ctx_len)[None, :]  # [N, C]
        in_bounds = (src >= 0) & (src < N)
        src_c = jnp.clip(src, 0, N - 1)
        same_seq = (seg[src_c] == seg[:, None]) & (seg[:, None] < num)
        gather = jnp.where(in_bounds & same_seq, src_c, N)
    else:
        splits = np.asarray(lod[_last_level(lod)])
        # static gather table: row n, slot j -> source row (or N = pad)
        gather = np.full((N, ctx_len), N, dtype=np.int32)
        for i in range(len(splits) - 1):
            for n in range(splits[i], splits[i + 1]):
                for j in range(ctx_len):
                    src = n + ctx_start + j
                    if splits[i] <= src < splits[i + 1]:
                        gather[n, j] = src
        gather = jnp.asarray(gather)
    padded = jnp.concatenate([x, jnp.zeros((1,) + x.shape[1:], x.dtype)])
    windows = padded[gather]                       # [N, ctx_len, D]
    return windows.reshape(N, -1)


@register_op("sequence_conv", infer_shape=_infer_seq_conv)
def sequence_conv_lower(ctx: LowerContext):
    """Per-sequence sliding-window projection
    (reference ``operators/math/context_project.h``): gather the
    [contextLength, D] window around each token (zero-padded at sequence
    boundaries), flatten, and matmul with the filter [ctx_len*D, F]."""
    x = ctx.input("X")          # [N, D]
    filt = ctx.input("Filter")  # [ctx_len*D, F]
    lod = _require_lod(ctx)
    ctx_len = ctx.attr("contextLength")
    ctx_start = ctx.attr("contextStart", -((ctx_len - 1) // 2))
    flat = _context_windows(ctx, x, lod, ctx_len, ctx_start)
    out = flat @ filt
    if ctx.op.input("PaddingData"):
        pass  # trainable boundary padding unsupported; zeros used
    ctx.set_output("Out", out)
    ctx.set_output_lod("Out",
                       lod if _is_dyn(lod) else [list(s) for s in lod])


def _infer_seq_context(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    out.shape = (x.shape[0], x.shape[1] * op.attr("contextLength"))
    out.dtype = x.dtype
    out.lod_level = x.lod_level


@register_op("sequence_context", infer_shape=_infer_seq_context)
def sequence_context_lower(ctx: LowerContext):
    """Raw context-window concatenation — the legacy DSL's
    ``context_projection`` without trainable weights (reference
    ``trainer_config_helpers/layers.py`` context_projection over
    ``operators/math/context_project.h``)."""
    x = ctx.input("X")
    lod = _require_lod(ctx)
    ctx_len = ctx.attr("contextLength")
    ctx_start = ctx.attr("contextStart", -((ctx_len - 1) // 2))
    out = _context_windows(ctx, x, lod, ctx_len, ctx_start)
    ctx.set_output("Out", out)
    ctx.set_output_lod("Out",
                       lod if _is_dyn(lod) else [list(s) for s in lod])


# ---------------------------------------------------------------------------
# sequence_expand_as / sequence_pad-ish helpers used by layers
# ---------------------------------------------------------------------------

@register_op("sequence_first_step", infer_shape=_infer_ragged)
def sequence_first_step_lower(ctx: LowerContext):
    x = ctx.input("X")
    lod = _require_lod(ctx)
    _, _, _, splits, _ = _segment_tables(ctx, lod, x.shape[0])
    ctx.set_output("Out", x[splits[:-1]])


@register_op("sequence_last_step", infer_shape=_infer_ragged)
def sequence_last_step_lower(ctx: LowerContext):
    x = ctx.input("X")
    lod = _require_lod(ctx)
    _, _, _, splits, _ = _segment_tables(ctx, lod, x.shape[0])
    ctx.set_output("Out", x[splits[1:] - 1])


# ---------------------------------------------------------------------------
# im2sequence — reference ``im2sequence_op.h``: image patches as a LoD
# sequence per image, rows ordered (oh, ow), features (C, kh, kw) (OCF).
# ---------------------------------------------------------------------------

def _im2seq_out(size, k, pad0, pad1, stride):
    return (size + pad0 + pad1 - k) // stride + 1


def _infer_im2sequence(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    n, c, h, w = x.shape
    k = op.attr("kernels")
    s = op.attr("strides", [1, 1])
    p = op.attr("paddings", [0, 0, 0, 0])
    oh = _im2seq_out(h, k[0], p[0], p[2], s[0])
    ow = _im2seq_out(w, k[1], p[1], p[3], s[1])
    out = block.var(op.output("Out")[0])
    out.shape = (n * oh * ow, c * k[0] * k[1])
    out.dtype = x.dtype
    out.lod_level = 1


@register_op("im2sequence", infer_shape=_infer_im2sequence)
def im2sequence_lower(ctx: LowerContext):
    x = ctx.input("X")                   # [N, C, H, W]
    k = list(ctx.attr("kernels"))
    s = list(ctx.attr("strides", [1, 1]))
    p = list(ctx.attr("paddings", [0, 0, 0, 0]))
    n, c = x.shape[0], x.shape[1]
    # conv_general_dilated_patches: feature index = c*kh*kw + i*kw + j
    # (channel slowest) == the reference's OCF (C, kh, kw) layout
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=[(p[0], p[2]), (p[1], p[3])])   # [N, C*kh*kw, OH, OW]
    oh, ow = patches.shape[2], patches.shape[3]
    out = jnp.moveaxis(patches, 1, 3).reshape(n * oh * ow,
                                              c * k[0] * k[1])
    ctx.set_output("Out", out)
    ctx.set_output_lod("Out", [[i * oh * ow for i in range(n + 1)]])


# ---------------------------------------------------------------------------
# row_conv — reference ``row_conv_op.cc``: per-sequence lookahead
# convolution out[t] = sum_w filter[w] * x[t + w]  (w < future_context).
# ---------------------------------------------------------------------------

def _infer_row_conv(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype
    out.lod_level = x.lod_level


@register_op("row_conv", infer_shape=_infer_row_conv)
def row_conv_lower(ctx: LowerContext):
    x = ctx.input("X")                   # [N, D] ragged
    filt = ctx.input("Filter")           # [future_context, D]
    lod = _require_lod(ctx, "X")
    fc = filt.shape[0]
    splits = lod[-1]
    outs = []
    for i in range(len(splits) - 1):
        lo, hi = int(splits[i]), int(splits[i + 1])
        seq = jax.lax.slice_in_dim(x, lo, hi, axis=0)   # [T, D]
        t = hi - lo
        acc = jnp.zeros_like(seq)
        for w in range(min(fc, t)):
            shifted = jnp.concatenate(
                [seq[w:], jnp.zeros((w, seq.shape[1]), seq.dtype)], axis=0)
            acc = acc + filt[w][None, :] * shifted
        outs.append(acc)
    out = jnp.concatenate(outs, axis=0)
    ctx.set_output("Out", out)
    ctx.set_output_lod("Out", [list(l) for l in lod])


def _infer_kmax(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    out.shape = (-1, op.attr("beam_size"))
    out.dtype = "int64"


@register_op("kmax_seq_score", infer_shape=_infer_kmax)
def kmax_seq_score_lower(ctx: LowerContext):
    """Per-sequence top-k of [N, 1] scores, returning the WITHIN-SEQUENCE
    INDEXES of the winners padded with -1 (reference
    KmaxSeqScoreLayer.cpp semantics — downstream layers select
    sub-sequences by these ids).  Pad to dense [B, T] once (NEG_INF
    fill) and take a single topk — static shapes regardless of
    raggedness."""
    x = ctx.input("X").reshape(-1)
    lod = _require_lod(ctx)
    k = ctx.attr("beam_size")
    n = x.shape[0]
    seg, _, num, splits, valid = _segment_tables(ctx, lod, n)
    if valid is None:
        valid = jnp.ones(n, bool)
    if _is_dyn(lod):
        t = lod.maxlen_bucket
    else:
        t = max(_lengths(lod, _last_level(lod)), default=1)
    segc = jnp.clip(seg, 0, num - 1)
    col = jnp.arange(n) - splits[segc]
    dense = jnp.full((num, max(t, k)), -1e30, x.dtype)
    # scatter-MAX, not set: clamped padding rows land on (0, 0) with the
    # fill value, and max() cannot clobber a real score there (a .set
    # with duplicate indices picks an unspecified writer)
    dense = dense.at[jnp.where(valid, segc, 0),
                     jnp.where(valid, col, 0)].max(
        jnp.where(valid, x, jnp.asarray(-1e30, x.dtype)))
    top, idx = jax.lax.top_k(dense, k)
    ids = jnp.where(top <= -1e29, -1, idx)   # short sequences pad with -1
    ctx.set_output("Out", ids.astype(jnp.int64))


@register_op("sub_nested_seq", infer_shape=_infer_ragged,
             no_grad_inputs=("SelectedIndices",), host=True)
def sub_nested_seq_lower(ctx: LowerContext):
    """Trim a 2-level nested sequence to the sub-sequences named by
    ``SelectedIndices`` [B, k] (within-outer-sequence ids, -1 = pad) —
    the beam-training companion of kmax_seq_score (reference
    SubNestedSequenceLayer.cpp).  Output is a 1-level sequence of the
    selected sub-sequences, in (outer, selection) order.  Host op: the
    output row count is data-dependent."""
    x = ctx.input("X")
    lod = _require_lod(ctx)
    if _is_dyn(lod):
        raise NotImplementedError(
            "sub_nested_seq needs a static 2-level LoD (beam decode runs "
            "in interpret/eval mode, like the reference's CPU layer)")
    if _last_level(lod) < 1:
        raise ValueError("sub_nested_seq input must be a 2-level nested "
                         "sequence")
    sel = np.asarray(ctx.input("SelectedIndices"))
    n_outer = len(lod[0]) - 1
    if sel.ndim != 2 or sel.shape[0] != n_outer:
        raise ValueError(
            f"sub_nested_seq: SelectedIndices must be [num_outer_seqs, k] "
            f"= [{n_outer}, k], got shape {tuple(sel.shape)} — one row of "
            f"selections per OUTER sequence (kmax over per-sub-seq scores "
            f"with a 1-level lod grouped by outer sequence)")
    outer = np.asarray(lod[0])   # outer seq -> sub-seq span
    inner = np.asarray(lod[1])   # sub-seq -> row span
    rows, new_splits = [], [0]
    for b in range(len(outer) - 1):
        for idx in sel[b]:
            idx = int(idx)
            if idx < 0:
                continue
            sub = outer[b] + idx
            if sub >= outer[b + 1]:
                raise ValueError(
                    f"sub_nested_seq: selected index {idx} out of range "
                    f"for outer sequence {b}")
            rows.extend(range(int(inner[sub]), int(inner[sub + 1])))
            new_splits.append(len(rows))
    ctx.set_output("Out", x[jnp.asarray(np.asarray(rows, np.int32))])
    ctx.set_output_lod("Out", [new_splits])
