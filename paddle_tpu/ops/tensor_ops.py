"""Shape / data-movement / creation ops.

Reference: ``paddle/fluid/operators/{reshape,transpose,concat,split,expand,
pad,crop,gather,scatter,cast,assign,fill_*,uniform_random,gaussian_random,
one_hot,top_k,...}_op``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, register_grad_lower, infer_shape_unary, ShapeInferenceSkip,
    lookup)


def _np_dtype(name):
    import jax.numpy as jnp
    return jnp.bfloat16 if name == "bfloat16" else np.dtype(name)


# ---------------------------------------------------------------------------
# creation ops
# ---------------------------------------------------------------------------

def _infer_fill_constant(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attr("shape"))
    out.dtype = op.attr("dtype", "float32")


@register_op("fill_constant", infer_shape=_infer_fill_constant,
             no_gradient=True)
def fill_constant_lower(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    value = ctx.attr("value", 0.0)
    ctx.set_output("Out", jnp.full(shape, value, dtype=dtype))


def _infer_fill_batch_like(op, block):
    x = block.var(op.input("Input")[0])
    shape = list(op.attr("shape"))
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    if x.shape is not None:
        shape[out_idx] = x.shape[in_idx]
    out = block.var(op.output("Out")[0])
    out.shape = tuple(shape)
    out.dtype = op.attr("dtype", "float32")


@register_op("fill_constant_batch_size_like",
             infer_shape=_infer_fill_batch_like, no_gradient=True)
def fill_constant_batch_size_like_lower(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(tuple(shape), ctx.attr("value", 0.0),
                                   dtype=dtype))


@register_op("fill_zeros_like", infer_shape=infer_shape_unary(),
             no_gradient=True)
def fill_zeros_like_lower(ctx):
    ctx.set_output("Out", jnp.zeros_like(ctx.input("X")))


@register_op("fill", infer_shape=_infer_fill_constant, no_gradient=True)
def fill_lower(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    value = np.asarray(ctx.attr("value"), dtype=dtype).reshape(shape)
    ctx.set_output("Out", jnp.asarray(value))


@register_op("assign", infer_shape=infer_shape_unary())
def assign_lower(ctx):
    ctx.set_output("Out", ctx.input("X"))


def _infer_assign_value(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attr("shape"))
    out.dtype = op.attr("dtype", "float32")


@register_op("assign_value", infer_shape=_infer_assign_value,
             no_gradient=True)
def assign_value_lower(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = ctx.attr("dtype", "float32")
    if dtype in ("float32", "float64", "bfloat16", "float16"):
        values = ctx.attr("fp32_values")
    else:
        values = ctx.attr("int32_values")
    arr = np.asarray(values, dtype=_np_dtype(dtype)).reshape(shape)
    ctx.set_output("Out", jnp.asarray(arr))


@register_op("uniform_random", infer_shape=_infer_fill_constant,
             no_gradient=True, uses_rng=True)
def uniform_random_lower(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    lo = ctx.attr("min", -1.0)
    hi = ctx.attr("max", 1.0)
    seed = ctx.attr("seed", 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.rng_key()
    ctx.set_output("Out", jax.random.uniform(key, shape, dtype=jnp.float32,
                                             minval=lo, maxval=hi).astype(dtype))


@register_op("gaussian_random", infer_shape=_infer_fill_constant,
             no_gradient=True, uses_rng=True)
def gaussian_random_lower(ctx):
    shape = tuple(ctx.attr("shape"))
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    mean = ctx.attr("mean", 0.0)
    std = ctx.attr("std", 1.0)
    seed = ctx.attr("seed", 0)
    key = jax.random.PRNGKey(seed) if seed else ctx.rng_key()
    out = jax.random.normal(key, shape, dtype=jnp.float32) * std + mean
    ctx.set_output("Out", out.astype(dtype))


@register_op("uniform_random_batch_size_like",
             infer_shape=_infer_fill_batch_like, no_gradient=True,
             uses_rng=True)
def uniform_random_batch_size_like_lower(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jax.random.uniform(
        ctx.rng_key(), tuple(shape), dtype=jnp.float32,
        minval=ctx.attr("min", -1.0), maxval=ctx.attr("max", 1.0)).astype(dtype))


@register_op("gaussian_random_batch_size_like",
             infer_shape=_infer_fill_batch_like, no_gradient=True,
             uses_rng=True)
def gaussian_random_batch_size_like_lower(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = _np_dtype(ctx.attr("dtype", "float32"))
    out = jax.random.normal(ctx.rng_key(), tuple(shape), dtype=jnp.float32) \
        * ctx.attr("std", 1.0) + ctx.attr("mean", 0.0)
    ctx.set_output("Out", out.astype(dtype))


# ---------------------------------------------------------------------------
# cast / shape
# ---------------------------------------------------------------------------

def _infer_cast(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = op.attr("out_dtype", "float32")


def _cast_grad_maker(op, block, no_grad_set):
    """cast grad = cast back (reference cast_op.cc CastOpGradMaker)."""
    from paddle_tpu.framework import grad_var_name
    x = op.input("X")[0]
    if x in no_grad_set:
        return [], {}
    g_out = grad_var_name(op.output("Out")[0])
    g_x = grad_var_name(x)
    in_dtype = op.attr("in_dtype", "float32")
    desc = {"type": "cast", "inputs": {"X": [g_out]},
            "outputs": {"Out": [g_x]},
            "attrs": {"in_dtype": op.attr("out_dtype"), "out_dtype": in_dtype}}
    return [desc], {x: g_x}


@register_op("cast", infer_shape=_infer_cast, grad_maker=_cast_grad_maker)
def cast_lower(ctx):
    ctx.set_output("Out", ctx.input("X").astype(
        _np_dtype(ctx.attr("out_dtype", "float32"))))


def _infer_shape_op(op, block):
    x = block.var(op.input("Input")[0])
    out = block.var(op.output("Out")[0])
    out.shape = (len(x.shape),) if x.shape is not None else None
    out.dtype = "int64"


@register_op("shape", infer_shape=_infer_shape_op, no_gradient=True)
def shape_lower(ctx):
    x = ctx.input("Input")
    ctx.set_output("Out", jnp.asarray(x.shape, dtype=jnp.int64))


# ---------------------------------------------------------------------------
# reshape / transpose / squeeze / unsqueeze
# ---------------------------------------------------------------------------

def _resolve_reshape(shape, in_shape):
    shape = list(shape)
    for i, d in enumerate(shape):
        if d == 0:
            shape[i] = in_shape[i]
    return shape


def _infer_reshape(op, block):
    x = block.var(op.input("X")[0])
    shape = op.attr("shape")
    out = block.var(op.output("Out")[0])
    if x.shape is None or any(d == -1 for d in x.shape):
        out.shape = tuple(shape)
    else:
        out.shape = tuple(np.reshape(np.empty(x.shape, dtype=np.int8),
                                     _resolve_reshape(shape, x.shape)).shape)
    out.dtype = x.dtype


@register_op("reshape", infer_shape=_infer_reshape)
def reshape_lower(ctx):
    x = ctx.input("X")
    shape = _resolve_reshape(ctx.attr("shape"), x.shape)
    out = x.reshape(shape)
    ctx.set_output("Out", out)
    # row identity preserved => ragged metadata survives (reference keeps
    # LoD through reshape when dim 0 is untouched)
    lod = ctx.input_lod("X")
    if lod is not None and out.ndim >= 1 and out.shape[0] == x.shape[0]:
        ctx.set_output_lod("Out", lod)


def _infer_transpose(op, block):
    x = block.var(op.input("X")[0])
    axis = op.attr("axis")
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        out.shape = tuple(x.shape[a] for a in axis)
    out.dtype = x.dtype


@register_op("transpose", infer_shape=_infer_transpose)
def transpose_lower(ctx):
    ctx.set_output("Out", jnp.transpose(ctx.input("X"), ctx.attr("axis")))


def _infer_squeeze(op, block):
    x = block.var(op.input("X")[0])
    axes = op.attr("axes", [])
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        if axes:
            out.shape = tuple(d for i, d in enumerate(x.shape)
                              if not (i in axes and d == 1))
        else:
            out.shape = tuple(d for d in x.shape if d != 1)
    out.dtype = x.dtype


@register_op("squeeze", infer_shape=_infer_squeeze)
def squeeze_lower(ctx):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if axes:
        out = x
        for a in sorted([a % x.ndim for a in axes], reverse=True):
            if out.shape[a] == 1:
                out = jnp.squeeze(out, axis=a)
    else:
        out = jnp.squeeze(x)
    ctx.set_output("Out", out)


def _infer_unsqueeze(op, block):
    x = block.var(op.input("X")[0])
    axes = op.attr("axes", [])
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        shape = list(x.shape)
        for a in sorted(axes):
            shape.insert(a, 1)
        out.shape = tuple(shape)
    out.dtype = x.dtype


@register_op("unsqueeze", infer_shape=_infer_unsqueeze)
def unsqueeze_lower(ctx):
    x = ctx.input("X")
    for a in sorted(ctx.attr("axes", [])):
        x = jnp.expand_dims(x, a)
    ctx.set_output("Out", x)


# ---------------------------------------------------------------------------
# concat / split / expand / pad / crop / slice
# ---------------------------------------------------------------------------

def _infer_concat(op, block):
    xs = [block.var(n) for n in op.input("X")]
    axis = op.attr("axis", 0)
    out = block.var(op.output("Out")[0])
    if all(x.shape is not None for x in xs):
        shape = list(xs[0].shape)
        shape[axis] = sum(x.shape[axis] for x in xs) \
            if all(x.shape[axis] != -1 for x in xs) else -1
        out.shape = tuple(shape)
    out.dtype = xs[0].dtype
    out.lod_level = xs[0].lod_level


@register_op("concat", infer_shape=_infer_concat)
def concat_lower(ctx):
    xs = ctx.inputs("X")
    ctx.set_output("Out", jnp.concatenate(xs, axis=ctx.attr("axis", 0)))


def _infer_split(op, block):
    x = block.var(op.input("X")[0])
    axis = op.attr("axis", 0)
    num = op.attr("num", 0)
    sections = op.attr("sections", [])
    outs = [block.var(n) for n in op.output("Out")]
    if x.shape is not None:
        for i, o in enumerate(outs):
            shape = list(x.shape)
            if num:
                shape[axis] = x.shape[axis] // num if x.shape[axis] != -1 else -1
            elif sections:
                shape[axis] = sections[i]
            o.shape = tuple(shape)
            o.dtype = x.dtype


@register_op("split", infer_shape=_infer_split)
def split_lower(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections", [])
    if num:
        outs = jnp.split(x, num, axis=axis)
    else:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    ctx.set_outputs("Out", outs)


def _infer_expand(op, block):
    x = block.var(op.input("X")[0])
    times = op.attr("expand_times")
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        out.shape = tuple(d * t if d != -1 else -1
                          for d, t in zip(x.shape, times))
    out.dtype = x.dtype


@register_op("expand", infer_shape=_infer_expand)
def expand_lower(ctx):
    ctx.set_output("Out", jnp.tile(ctx.input("X"),
                                   tuple(ctx.attr("expand_times"))))


def _infer_pad(op, block):
    x = block.var(op.input("X")[0])
    paddings = op.attr("paddings")
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        out.shape = tuple(
            d + paddings[2 * i] + paddings[2 * i + 1] if d != -1 else -1
            for i, d in enumerate(x.shape))
    out.dtype = x.dtype


@register_op("pad", infer_shape=_infer_pad)
def pad_lower(ctx):
    x = ctx.input("X")
    p = ctx.attr("paddings")
    pad_width = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, pad_width, mode="constant",
                                  constant_values=ctx.attr("pad_value", 0.0)))


def _infer_crop(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attr("shape"))
    out.dtype = block.var(op.input("X")[0]).dtype


@register_op("crop", infer_shape=_infer_crop)
def crop_lower(ctx):
    x = ctx.input("X")
    offsets = ctx.attr("offsets", [0] * x.ndim)
    shape = ctx.attr("shape")
    slices = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_output("Out", x[slices])


def _infer_slice(op, block):
    x = block.var(op.input("Input")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        shape = list(x.shape)
        for ax, st, en in zip(op.attr("axes"), op.attr("starts"),
                              op.attr("ends")):
            d = shape[ax]
            if d == -1:
                continue
            st2 = st if st >= 0 else st + d
            en2 = min(en if en >= 0 else en + d, d)
            shape[ax] = max(en2 - st2, 0)
        out.shape = tuple(shape)
    out.dtype = x.dtype


@register_op("slice", infer_shape=_infer_slice)
def slice_lower(ctx):
    x = ctx.input("Input")
    slices = [slice(None)] * x.ndim
    for ax, st, en in zip(ctx.attr("axes"), ctx.attr("starts"),
                          ctx.attr("ends")):
        slices[ax] = slice(st, en)
    ctx.set_output("Out", x[tuple(slices)])


# ---------------------------------------------------------------------------
# gather / scatter / multiplex / one_hot
# ---------------------------------------------------------------------------

def _infer_gather(op, block):
    x = block.var(op.input("X")[0])
    ids = block.var(op.input("Index")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is not None and ids.shape is not None:
        out.shape = (ids.shape[0],) + tuple(x.shape[1:])
    out.dtype = x.dtype


@register_op("gather", infer_shape=_infer_gather, no_grad_inputs=("Index",))
def gather_lower(ctx):
    x, idx = ctx.input("X"), ctx.input("Index")
    ctx.set_output("Out", jnp.take(x, idx.reshape(-1), axis=0))


@register_op("scatter", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("Ids",))
def scatter_lower(ctx):
    x = ctx.input("X")
    ids = ctx.input("Ids").reshape(-1)
    upd = ctx.input("Updates")
    ctx.set_output("Out", x.at[ids].set(upd))


def _infer_multiplex(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype


@register_op("multiplex", infer_shape=_infer_multiplex,
             no_grad_inputs=("Ids",))
def multiplex_lower(ctx):
    xs = jnp.stack(ctx.inputs("X"))  # (K, B, ...)
    ids = ctx.input("Ids").reshape(-1)  # (B,)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output("Out", xs[ids, rows])


def _infer_one_hot(op, block):
    x = block.var(op.input("X")[0])
    depth = op.attr("depth")
    out = block.var(op.output("Out")[0])
    if x.shape is not None:
        out.shape = tuple(x.shape[:-1]) + (depth,)
    out.dtype = "float32"


@register_op("one_hot", infer_shape=_infer_one_hot, no_gradient=True)
def one_hot_lower(ctx):
    x = ctx.input("X")
    depth = ctx.attr("depth")
    if x.shape and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    ctx.set_output("Out", jax.nn.one_hot(x, depth, dtype=jnp.float32))


# ---------------------------------------------------------------------------
# top_k / argsort / arg_min_max
# ---------------------------------------------------------------------------

def _infer_top_k(op, block):
    x = block.var(op.input("X")[0])
    k = op.attr("k", 1)
    out = block.var(op.output("Out")[0])
    idx = block.var(op.output("Indices")[0])
    if x.shape is not None:
        out.shape = tuple(x.shape[:-1]) + (k,)
        idx.shape = out.shape
    out.dtype = x.dtype
    idx.dtype = "int64"


@register_op("top_k", infer_shape=_infer_top_k, no_gradient=True)
def top_k_lower(ctx):
    x = ctx.input("X")
    vals, idx = jax.lax.top_k(x, ctx.attr("k", 1))
    ctx.set_output("Out", vals)
    ctx.set_output("Indices", idx.astype(jnp.int64))


@register_op("argsort", no_gradient=True)
def argsort_lower(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    ctx.set_output("Out", jnp.sort(x, axis=axis))
    ctx.set_output("Indices", idx.astype(jnp.int64))


@register_op("arg_max", no_gradient=True)
def arg_max_lower(ctx):
    ctx.set_output("Out", jnp.argmax(ctx.input("X"),
                                     axis=ctx.attr("axis", -1)).astype(jnp.int64))


@register_op("arg_min", no_gradient=True)
def arg_min_lower(ctx):
    ctx.set_output("Out", jnp.argmin(ctx.input("X"),
                                     axis=ctx.attr("axis", -1)).astype(jnp.int64))


# ---------------------------------------------------------------------------
# lookup_table (embedding)  — reference lookup_table_op.cc; the sparse
# SelectedRows gradient path is realized as dense scatter-add here (XLA
# lowers jnp.take VJP to scatter-add on TPU); the SelectedRows-typed variant
# lives with the sparse subsystem.
# ---------------------------------------------------------------------------

def _infer_lookup_table(op, block):
    w = block.var(op.input("W")[0])
    ids = block.var(op.input("Ids")[0])
    out = block.var(op.output("Out")[0])
    if w.shape is not None and ids.shape is not None:
        ids_shape = ids.shape
        if ids_shape and ids_shape[-1] == 1:
            ids_shape = ids_shape[:-1]
        out.shape = tuple(ids_shape) + (w.shape[-1],)
    out.dtype = w.dtype
    out.lod_level = ids.lod_level


@register_op("lookup_table", infer_shape=_infer_lookup_table,
             no_grad_inputs=("Ids",))
def lookup_table_lower(ctx):
    w, ids = ctx.input("W"), ctx.input("Ids")
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = ctx.attr("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    ctx.set_output("Out", out)


def _lookup_table_grad_lower(ctx):
    """``is_sparse=True`` emits a SelectedRows gradient (reference
    lookup_table_op.cc SelectedRows branch) — O(batch·dim), no dense
    [vocab, dim] scatter; dense mode falls back to auto-vjp."""
    from paddle_tpu.ops.registry import auto_vjp_grad_lower
    if not ctx.attr("is_sparse", False):
        return auto_vjp_grad_lower("lookup_table")(ctx)
    from paddle_tpu.selected_rows import SelectedRows
    w = ctx.input("W")
    ids = ctx.input("Ids")
    dout = ctx.input("Out@GRAD")
    gname = ctx.op.output("W@GRAD")
    if not gname or not gname[0]:
        return
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    rows = ids.reshape(-1).astype(jnp.int32)
    vals = dout.reshape(-1, w.shape[-1])
    padding_idx = ctx.attr("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        vals = vals * (rows != padding_idx)[:, None].astype(vals.dtype)
    ctx.outputs[gname[0]] = SelectedRows(rows, vals, w.shape[0])


lookup("lookup_table").grad_lower = _lookup_table_grad_lower


# ---------------------------------------------------------------------------
# sampling_id — reference ``sampling_id_op.cc`` / gserver
# SamplingIdLayer.cpp: sample one class id per row from a probability row.
# Inverse-CDF with PER-ROW uniforms off the traced RNG key (dense ops, no
# host round-trip).
# ---------------------------------------------------------------------------

def _infer_sampling_id(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    out = block.var(op.output("Out")[0])
    out.shape = (x.shape[0], 1)
    out.dtype = "int64"


@register_op("sampling_id", infer_shape=_infer_sampling_id,
             no_gradient=True, uses_rng=True)
def sampling_id_lower(ctx):
    x = ctx.input("X")                       # [N, C] probabilities
    # CDF + uniforms in float32 regardless of input dtype: bf16 cumsum
    # over a large vocab accumulates ~2^-8 rounding that visibly biases
    # the sampled distribution.
    u = jax.random.uniform(ctx.rng_key(), (x.shape[0], 1),
                           dtype=jnp.float32)
    cdf = jnp.cumsum(x.astype(jnp.float32), axis=1)
    idx = jnp.sum((cdf < u).astype(jnp.int32), axis=1, keepdims=True)
    # int64 to match the declared IR dtype (jax truncates to int32 when
    # x64 is disabled, the framework-wide convention — cf. arg_max)
    ctx.set_output("Out", jnp.clip(idx, 0, x.shape[1] - 1)
                   .astype(jnp.int64))
