"""CSP ops: channel_create/send/recv/close, go, select.

Reference: ``operators/channel_{create,send,recv,close}_op.cc``,
``go_op.cc``, ``select_op.cc`` over ``framework/channel.h``.

All are HOST ops (the reference registers them CPU-only and drives them
from its interpreter threads): a block using them runs in the executor's
op-by-op interpret mode, with ``go`` bodies on Python daemon threads and
channels coordinating through ``paddle_tpu.channel.Channel``.  This layer
is host-side control orchestration — device compute inside go/select
bodies still lowers through the normal op registry (eagerly here).
"""

from __future__ import annotations

import threading
import time

import numpy as np
import jax.numpy as jnp

from paddle_tpu.channel import Channel
from paddle_tpu.ops.registry import register_op, ShapeInferenceSkip


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


@register_op("channel_create", infer_shape=_infer_skip, no_gradient=True,
             host=True)
def channel_create_lower(ctx):
    out = ctx.op.output("Out")[0]
    # idempotent across steps: reuse the channel living in the scope
    scope = ctx.aux.get("scope")
    existing = scope.find_var(out) if scope is not None else None
    if isinstance(existing, Channel):
        ctx.outputs[out] = existing
        return
    ctx.outputs[out] = Channel(capacity=ctx.attr("capacity", 0),
                               dtype=ctx.attr("data_type"))


@register_op("channel_send", infer_shape=_infer_skip, no_gradient=True,
             host=True)
def channel_send_lower(ctx):
    ch = ctx.env[ctx.op.input("Channel")[0]]
    value = ctx.input("X")
    ch.send(np.asarray(value))
    ctx.set_output("Status", jnp.asarray([True]))


@register_op("channel_recv", infer_shape=_infer_skip, no_gradient=True,
             host=True)
def channel_recv_lower(ctx):
    ch = ctx.env[ctx.op.input("Channel")[0]]
    value, ok = ch.receive()
    out_name = ctx.op.output("Out")[0]
    if ok:
        ctx.outputs[out_name] = jnp.asarray(value)
    else:
        # closed-and-drained: zero value of the placeholder's shape if known
        prev = ctx.env.get(out_name)
        ctx.outputs[out_name] = (jnp.zeros_like(prev) if prev is not None
                                 else jnp.zeros((1,), jnp.float32))
    ctx.set_output("Status", jnp.asarray([ok]))


@register_op("channel_close", infer_shape=_infer_skip, no_gradient=True,
             host=True)
def channel_close_lower(ctx):
    ctx.env[ctx.op.input("Channel")[0]].close()


def _run_block_on_thread(sub_block, env, aux, training):
    """go body: execute the sub-block eagerly on a daemon thread; writes
    to persistable vars go to the scope immediately so other routines see
    them (the reference shares one Scope across its threads)."""
    lower_block = aux["lower_block"]
    scope = aux.get("scope")

    def find_var(name):
        b = sub_block
        while b is not None:
            if b.has_var_local(name):
                return b.var(name)
            b = b.parent_block
        return None

    def body():
        thread_aux = dict(aux)
        thread_aux["rng_counter"] = 0
        for op in sub_block.ops:
            from paddle_tpu.ops import registry as _registry
            opdef = _registry.resolve_lowering(op.type)
            octx = _registry.LowerContext(op, env, sub_block, rng_key=None,
                                          training=training, aux=thread_aux)
            opdef.lower(octx)
            env.update(octx.outputs)
            if scope is not None:
                for n in octx.outputs:
                    v = find_var(n)
                    if v is not None and getattr(v, "persistable", False):
                        scope.set_var(n, env[n])

    t = threading.Thread(target=body, daemon=True)
    t.start()
    return t


@register_op("go", infer_shape=_infer_skip, no_gradient=True, host=True)
def go_lower(ctx):
    """Launch the sub-block as a goroutine (reference go_op.cc:
    ExecuteOnThread with a detached std::thread)."""
    sub_block = ctx.attr("sub_block")
    # closure snapshot; channels are shared objects and persistables
    # write/read through the shared scope (ScopeEnv)
    env = ctx.env.clone_for_thread() if hasattr(ctx.env, "clone_for_thread") \
        else dict(ctx.env)
    threads = ctx.aux.setdefault("go_threads", [])
    threads.append(_run_block_on_thread(sub_block, env, ctx.aux,
                                        ctx.training))


@register_op("select", infer_shape=_infer_skip, no_gradient=True, host=True)
def select_lower(ctx):
    """Block until one case can proceed, perform its channel action, then
    run that case's body block (reference select_op.cc semantics with the
    same 'idx,action,channel,value' case serialization; DEFAULT fires when
    no other case is immediately ready)."""
    cases = ctx.attr("cases", [])  # ["idx,action,ch_name,val_name", ...]
    parsed = []
    default_idx = None
    for c in cases:
        parts = c.split(",")
        idx, action = int(parts[0]), int(parts[1])
        ch_name = parts[2] if len(parts) > 2 else ""
        val_name = parts[3] if len(parts) > 3 else ""
        if action == 0:  # DEFAULT
            default_idx = idx
        parsed.append((idx, action, ch_name, val_name))

    lower_block = ctx.aux["lower_block"]

    def fire(idx, recv_name=None, recv_value=None, recv_ok=None):
        blk = ctx.op.attrs.get(f"case_block_{idx}")
        if recv_name:
            ctx.env[recv_name] = jnp.asarray(recv_value) if recv_ok else \
                jnp.zeros_like(ctx.env[recv_name]) \
                if ctx.env.get(recv_name) is not None else \
                jnp.zeros((1,), jnp.float32)
            ctx.outputs[recv_name] = ctx.env[recv_name]
        if blk is not None:
            lower_block(blk, ctx.env, None, ctx.training, ctx.aux)
            # surface case-body writes as op outputs so they reach the
            # surrounding env/state
            for op in blk.ops:
                for n in op.output_arg_names:
                    if n in ctx.env:
                        ctx.outputs[n] = ctx.env[n]

    # hoist send-value host transfers out of the poll loop
    send_values = {val_name: np.asarray(ctx.env[val_name])
                   for _, action, __, val_name in parsed if action == 1}
    deadline = time.monotonic() + float(ctx.attr("timeout_s", 60.0))
    while True:
        for idx, action, ch_name, val_name in parsed:
            if action == 1:  # SEND
                ch = ctx.env[ch_name]
                if ch.try_send(send_values[val_name]):
                    fire(idx)
                    return
            elif action == 2:  # RECEIVE
                ch = ctx.env[ch_name]
                value, ok, ready = ch.try_receive()
                if ready:
                    fire(idx, recv_name=val_name, recv_value=value,
                         recv_ok=ok)
                    return
        if default_idx is not None:
            fire(default_idx)
            return
        if time.monotonic() > deadline:
            raise RuntimeError("select: no case became ready (deadlock?)")
        time.sleep(0.0005)
