"""Detection / vision op group — the reference's SSD pipeline.

Reference kernels: ``paddle/fluid/operators/prior_box_op.h``,
``box_coder_op.h``, ``iou_similarity_op.h``, ``bipartite_match_op.cc``,
``target_assign_op.h``, ``mine_hard_examples_op.cc``, ``multiclass_nms_op.cc``,
``roi_pool_op.h``, ``detection_map_op.h``; Python wrappers
``python/paddle/fluid/layers/detection.py``.

TPU re-design notes
-------------------
* ``prior_box`` depends only on static shapes + attrs, so boxes are computed
  in numpy at trace time and emitted as XLA constants (folded into the graph).
* ``bipartite_match`` — the reference's greedy CPU loop becomes a
  ``lax.fori_loop`` with a static trip bound of min(rows, cols) per LoD
  instance, so the whole op stays inside the jitted computation (the
  reference pins it to CPUPlace).
* ``mine_hard_examples`` emits ``NegIndices`` as a DENSE ``[N, P]`` int32
  tensor padded with -1 (indices sorted by descending loss) instead of the
  reference's ragged LoD tensor — static shapes for XLA; ``target_assign``
  accepts this dense form (and the flat LoD form for parity).
* ``multiclass_nms`` / ``detection_map`` produce data-dependent row counts,
  so they are host ops (eager numpy), mirroring the reference which registers
  both as CPU-only kernels.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, register_grad_lower, ShapeInferenceSkip)

_KEPS = 1e-6


# ---------------------------------------------------------------------------
# iou_similarity
# ---------------------------------------------------------------------------

def _infer_iou(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.input("Y")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is None or y.shape is None:
        raise ShapeInferenceSkip()
    out.shape = (x.shape[0], y.shape[0])
    out.dtype = x.dtype
    out.lod_level = x.lod_level


def _iou_matrix(a, b):
    """Pairwise IoU of [N,4] x [M,4] boxes (xmin,ymin,xmax,ymax) —
    vectorized form of reference IOUSimilarity (iou_similarity_op.h:20)."""
    area1 = (a[:, 3] - a[:, 1]) * (a[:, 2] - a[:, 0])       # [N]
    area2 = (b[:, 3] - b[:, 1]) * (b[:, 2] - b[:, 0])       # [M]
    ixmin = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iymin = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ixmax = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iymax = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ixmax - ixmin, 0.0)
    ih = jnp.maximum(iymax - iymin, 0.0)
    inter = iw * ih
    union = area1[:, None] + area2[None, :] - inter
    return inter / union


@register_op("iou_similarity", infer_shape=_infer_iou, no_gradient=True)
def iou_similarity_lower(ctx):
    x = ctx.input("X")
    y = ctx.input("Y")
    ctx.set_output("Out", _iou_matrix(x, y))
    lod = ctx.input_lod("X")
    if lod is not None:
        ctx.set_output_lod("Out", lod)


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

def _infer_box_coder(op, block):
    tb = block.var(op.input("TargetBox")[0])
    pb = block.var(op.input("PriorBox")[0])
    out = block.var(op.output("OutputBox")[0])
    if tb.shape is None or pb.shape is None:
        raise ShapeInferenceSkip()
    ct = op.attr("code_type", "encode_center_size")
    if ct == "encode_center_size":
        out.shape = (tb.shape[0], pb.shape[0], 4)
    else:
        out.shape = tuple(tb.shape)
    out.dtype = tb.dtype
    out.lod_level = tb.lod_level


@register_op("box_coder", infer_shape=_infer_box_coder, no_gradient=True)
def box_coder_lower(ctx):
    """Reference box_coder_op.h EncodeCenterSize/DecodeCenterSize."""
    prior = ctx.input("PriorBox")          # [M, 4]
    pvar = ctx.input("PriorBoxVar")        # [M, 4] or None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    if pvar is None:
        pvar = jnp.ones_like(prior)
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = (prior[:, 2] + prior[:, 0]) / 2
    pcy = (prior[:, 3] + prior[:, 1]) / 2
    if code_type == "encode_center_size":
        # target [N,4] -> out [N, M, 4]
        tcx = (target[:, 2] + target[:, 0]) / 2
        tcy = (target[:, 3] + target[:, 1]) / 2
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pvar[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pvar[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    elif code_type == "decode_center_size":
        # target [N, M, 4] (deltas) -> out [N, M, 4] (corner boxes)
        tcx = pvar[None, :, 0] * target[..., 0] * pw[None, :] + pcx[None, :]
        tcy = pvar[None, :, 1] * target[..., 1] * ph[None, :] + pcy[None, :]
        tw = jnp.exp(pvar[None, :, 2] * target[..., 2]) * pw[None, :]
        th = jnp.exp(pvar[None, :, 3] * target[..., 3]) * ph[None, :]
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2, tcy + th / 2], axis=-1)
    else:
        raise ValueError(f"box_coder: unknown code_type {code_type!r}")
    ctx.set_output("OutputBox", out)
    lod = ctx.input_lod("TargetBox")
    if lod is not None:
        ctx.set_output_lod("OutputBox", lod)


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    """Reference ExpandAspectRatios (prior_box_op.h:23)."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def _infer_prior_box(op, block):
    inp = block.var(op.input("Input")[0])
    if inp.shape is None:
        raise ShapeInferenceSkip()
    min_sizes = op.attr("min_sizes", [])
    max_sizes = op.attr("max_sizes", []) or []
    ars = _expand_aspect_ratios(op.attr("aspect_ratios", []),
                                op.attr("flip", False))
    num_priors = len(ars) * len(min_sizes) + len(max_sizes)
    h, w = inp.shape[2], inp.shape[3]
    for slot in ("Boxes", "Variances"):
        v = block.var(op.output(slot)[0])
        v.shape = (h, w, num_priors, 4)
        v.dtype = "float32"


@register_op("prior_box", infer_shape=_infer_prior_box, no_gradient=True)
def prior_box_lower(ctx):
    """Shape/attr-only computation: done in numpy at trace time, emitted as
    constants (reference prior_box_op.h:56 loops per pixel at run time)."""
    inp = ctx.input("Input")
    img = ctx.input("Image")
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    min_sizes = [float(s) for s in ctx.attr("min_sizes", [])]
    max_sizes = [float(s) for s in (ctx.attr("max_sizes", []) or [])]
    ars = _expand_aspect_ratios(ctx.attr("aspect_ratios", []),
                                ctx.attr("flip", False))
    variances = [float(v) for v in
                 ctx.attr("variances", [0.1, 0.1, 0.2, 0.2])]
    step_w = float(ctx.attr("step_w", 0.0) or 0.0)
    step_h = float(ctx.attr("step_h", 0.0) or 0.0)
    offset = float(ctx.attr("offset", 0.5))
    sw = step_w if step_w > 0 else iw / fw
    sh = step_h if step_h > 0 else ih / fh

    # per-prior half-sizes in the reference's interleaved order: for each
    # min_size, all aspect ratios then (optionally) the max_size square
    half_w, half_h = [], []
    for s, ms in enumerate(min_sizes):
        for ar in ars:
            half_w.append(ms * math.sqrt(ar) / 2.0)
            half_h.append(ms / math.sqrt(ar) / 2.0)
        if max_sizes:
            sq = math.sqrt(ms * max_sizes[s]) / 2.0
            half_w.append(sq)
            half_h.append(sq)
    num_priors = len(half_w)
    hw = np.asarray(half_w, np.float32)[None, None, :]
    hh = np.asarray(half_h, np.float32)[None, None, :]
    cx = ((np.arange(fw, dtype=np.float32) + offset) * sw)[None, :, None]
    cy = ((np.arange(fh, dtype=np.float32) + offset) * sh)[:, None, None]
    boxes = np.stack(
        np.broadcast_arrays((cx - hw) / iw, (cy - hh) / ih,
                            (cx + hw) / iw, (cy + hh) / ih),
        axis=-1).astype(np.float32)
    if ctx.attr("clip", False):
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(
        np.asarray(variances, np.float32),
        (fh, fw, num_priors, 4)).copy()
    ctx.set_output("Boxes", jnp.asarray(boxes))
    ctx.set_output("Variances", jnp.asarray(vars_))


# ---------------------------------------------------------------------------
# bipartite_match
# ---------------------------------------------------------------------------

def _infer_bipartite(op, block):
    d = block.var(op.input("DistMat")[0])
    if d.shape is None:
        raise ShapeInferenceSkip()
    for slot, dt in (("ColToRowMatchIndices", "int32"),
                     ("ColToRowMatchDist", d.dtype)):
        v = block.var(op.output(slot)[0])
        v.shape = (-1, d.shape[1])
        v.dtype = dt


def _bipartite_match_one(dist):
    """Greedy max-dist matching for one instance ([rows, cols] dist) —
    reference BipartiteMatchKernel::BipartiteMatch (bipartite_match_op.cc),
    as a fori_loop with static bound min(rows, cols)."""
    rows, cols = dist.shape
    n_iter = min(rows, cols)

    def body(_, state):
        match_idx, match_dist, row_used = state
        eligible = ((~row_used[:, None]) & (match_idx == -1)[None, :]
                    & (dist >= _KEPS))
        masked = jnp.where(eligible, dist, -1.0)
        flat = jnp.argmax(masked)
        r, c = flat // cols, flat % cols
        ok = masked[r, c] >= _KEPS
        match_idx = jnp.where(
            ok, match_idx.at[c].set(r.astype(jnp.int32)), match_idx)
        match_dist = jnp.where(ok, match_dist.at[c].set(dist[r, c]),
                               match_dist)
        row_used = jnp.where(ok, row_used.at[r].set(True), row_used)
        return match_idx, match_dist, row_used

    init = (jnp.full((cols,), -1, jnp.int32),
            jnp.zeros((cols,), dist.dtype),
            jnp.zeros((rows,), bool))
    match_idx, match_dist, _ = jax.lax.fori_loop(0, n_iter, body, init)
    return match_idx, match_dist


def _argmax_match(dist, match_idx, match_dist, threshold):
    """Reference ArgMaxMatch: per-prediction extra matches for unmatched
    columns whose best row distance >= threshold."""
    col_best = jnp.max(dist, axis=0)
    col_arg = jnp.argmax(dist, axis=0).astype(jnp.int32)
    cond = (match_idx == -1) & (col_best >= threshold) & (col_best >= _KEPS)
    return (jnp.where(cond, col_arg, match_idx),
            jnp.where(cond, col_best, match_dist))


@register_op("bipartite_match", infer_shape=_infer_bipartite,
             no_gradient=True)
def bipartite_match_lower(ctx):
    dist = ctx.input("DistMat")
    lod = ctx.input_lod("DistMat")
    match_type = ctx.attr("match_type") or "bipartite"
    threshold = ctx.attr("dist_threshold")
    threshold = 0.5 if threshold is None else float(threshold)
    if lod is None:
        segments = [(0, dist.shape[0])]
    else:
        splits = lod[-1]
        segments = [(int(splits[i]), int(splits[i + 1]))
                    for i in range(len(splits) - 1)]
    idx_rows, dist_rows = [], []
    for lo, hi in segments:
        sub = jax.lax.slice_in_dim(dist, lo, hi, axis=0)
        mi, md = _bipartite_match_one(sub)
        if match_type == "per_prediction":
            mi, md = _argmax_match(sub, mi, md, threshold)
        idx_rows.append(mi)
        dist_rows.append(md)
    ctx.set_output("ColToRowMatchIndices", jnp.stack(idx_rows))
    ctx.set_output("ColToRowMatchDist", jnp.stack(dist_rows))


# ---------------------------------------------------------------------------
# target_assign
# ---------------------------------------------------------------------------

def _infer_target_assign(op, block):
    x = block.var(op.input("X")[0])
    mi = block.var(op.input("MatchIndices")[0])
    if x.shape is None or mi.shape is None:
        raise ShapeInferenceSkip()
    k = x.shape[-1]
    out = block.var(op.output("Out")[0])
    out.shape = (mi.shape[0], mi.shape[1], k)
    out.dtype = x.dtype
    ow = block.var(op.output("OutWeight")[0])
    ow.shape = (mi.shape[0], mi.shape[1], 1)
    ow.dtype = "float32"


@register_op("target_assign", infer_shape=_infer_target_assign,
             no_gradient=True)
def target_assign_lower(ctx):
    """out[i, j] = X[lod[i] + match[i, j]][j % P] where matched, else
    mismatch_value (reference target_assign_op.h)."""
    x = ctx.input("X")                       # [M, P, K] (LoD rows)
    match = ctx.input("MatchIndices")        # [N, Pm] int32
    mismatch = ctx.attr("mismatch_value", 0)
    lod = ctx.input_lod("X")
    n, pm = match.shape
    if x.ndim == 2:
        x = x[:, None, :]
    p_x, k = x.shape[1], x.shape[2]
    if lod is None:
        if n != 1:
            # reference target_assign_op.h enforces LoD on X; without it the
            # per-instance row offsets are unknowable for a real batch
            raise ValueError(
                "target_assign: X must carry LoD when MatchIndices has "
                f"{n} > 1 instances")
        starts = [0]
    else:
        starts = [int(s) for s in lod[-1][:-1]]
    col = jnp.arange(pm) % p_x               # j % P
    outs, weights = [], []
    for i in range(n):
        idx = match[i]                        # [Pm]
        rows = starts[i] + jnp.maximum(idx, 0)
        gathered = x[rows, col]               # [Pm, K]
        matched = (idx >= 0)[:, None]
        out_i = jnp.where(matched, gathered,
                          jnp.asarray(mismatch, x.dtype))
        w_i = matched.astype(jnp.float32)
        outs.append(out_i)
        weights.append(w_i)
    out = jnp.stack(outs)                     # [N, Pm, K]
    w = jnp.stack(weights)                    # [N, Pm, 1]

    neg = ctx.input("NegIndices")
    if neg is not None:
        neg_lod = ctx.input_lod("NegIndices")
        if neg.ndim == 2 and neg.shape[0] == n and neg_lod is None:
            # dense [N, P] -1-padded form from mine_hard_examples
            neg_masks = []
            for i in range(n):
                ids = neg[i].reshape(-1)
                valid = ids >= 0
                m = jnp.zeros((pm,), bool).at[
                    jnp.maximum(ids, 0)].max(valid)
                neg_masks.append(m)
            neg_mask = jnp.stack(neg_masks)   # [N, Pm]
        else:
            # flat [Neg, 1] + LoD form (reference layout)
            ids = neg.reshape(-1)
            splits = (neg_lod[-1] if neg_lod is not None
                      else [0, ids.shape[0]])
            rows_mask = []
            for i in range(n):
                lo, hi = int(splits[i]), int(splits[i + 1])
                seg = jax.lax.slice_in_dim(ids, lo, hi)
                m = jnp.zeros((pm,), bool).at[seg].set(True)
                rows_mask.append(m)
            neg_mask = jnp.stack(rows_mask)
        out = jnp.where(neg_mask[:, :, None],
                        jnp.asarray(mismatch, out.dtype), out)
        w = jnp.where(neg_mask[:, :, None], 1.0, w)
    ctx.set_output("Out", out)
    ctx.set_output("OutWeight", w)


# ---------------------------------------------------------------------------
# mine_hard_examples
# ---------------------------------------------------------------------------

def _infer_mine(op, block):
    mi = block.var(op.input("MatchIndices")[0])
    if mi.shape is None:
        raise ShapeInferenceSkip()
    for slot in ("NegIndices", "UpdatedMatchIndices"):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = tuple(mi.shape)
            v.dtype = "int32"


@register_op("mine_hard_examples", infer_shape=_infer_mine, no_gradient=True)
def mine_hard_examples_lower(ctx):
    """Reference mine_hard_examples_op.cc; NegIndices is emitted dense
    [N, P] (-1 padded, loss-descending order) — see module docstring."""
    cls_loss = ctx.input("ClsLoss")              # [N, P]
    loc_loss = ctx.input("LocLoss")              # optional [N, P]
    match = ctx.input("MatchIndices")            # [N, P] int32
    match_dist = ctx.input("MatchDist")          # [N, P]
    neg_pos_ratio = float(ctx.attr("neg_pos_ratio", 3.0))
    neg_dist_threshold = float(ctx.attr("neg_dist_threshold", 0.5))
    mining_type = ctx.attr("mining_type", "max_negative")
    sample_size = ctx.attr("sample_size") or 0
    n, p = match.shape

    if mining_type == "hard_example":
        if sample_size <= 0:
            # reference mine_hard_examples_op.cc enforces sample_size > 0
            raise ValueError(
                "mine_hard_examples: sample_size must be > 0 in "
                "hard_example mode")
        eligible = jnp.ones_like(match, bool)
        loss = cls_loss + (loc_loss if loc_loss is not None else 0.0)
    else:  # max_negative
        eligible = (match == -1) & (match_dist < neg_dist_threshold)
        loss = cls_loss

    masked_loss = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked_loss, axis=1)    # [N, P] desc
    num_elig = jnp.sum(eligible, axis=1)
    if mining_type == "hard_example":
        neg_sel = jnp.minimum(jnp.asarray(sample_size), num_elig)
    else:
        num_pos = jnp.sum(match != -1, axis=1)
        neg_sel = jnp.minimum(
            (num_pos.astype(jnp.float32) * neg_pos_ratio).astype(jnp.int32),
            num_elig)
    pos_in_order = jnp.arange(p)[None, :]
    selected_order = pos_in_order < neg_sel[:, None]

    if mining_type == "hard_example":
        # reference tail loop: selected+matched priors STAY positive (and
        # are excluded from NegIndices); unselected positives demote to -1;
        # NegIndices = selected ∩ unmatched
        rows = jnp.arange(n)[:, None]
        match_at_order = match[rows, order]
        neg_sel_mask = selected_order & (match_at_order == -1)
        neg_indices = jnp.where(neg_sel_mask, order, -1).astype(jnp.int32)
        sel_mask = jnp.zeros((n, p), bool).at[rows, order].max(selected_order)
        updated = jnp.where((match > -1) & ~sel_mask, -1, match)
    else:
        neg_indices = jnp.where(selected_order, order, -1).astype(jnp.int32)
        updated = match
    ctx.set_output("NegIndices", neg_indices)
    ctx.set_output("UpdatedMatchIndices", updated)


# ---------------------------------------------------------------------------
# multiclass_nms (host op — data-dependent output rows, like the
# reference's CPU-only kernel)
# ---------------------------------------------------------------------------

def _jaccard(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = iw * ih
    union = ((a[2] - a[0]) * (a[3] - a[1])
             + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / union if union > 0 else 0.0


def _nms_one_class(boxes, scores, score_threshold, nms_top_k, nms_threshold,
                   nms_eta):
    """Greedy NMS for one class: returns kept indices (into boxes)."""
    idx = np.where(scores > score_threshold)[0]
    idx = idx[np.argsort(-scores[idx], kind="stable")]
    if nms_top_k > -1 and len(idx) > nms_top_k:
        idx = idx[:nms_top_k]
    kept = []
    adaptive_threshold = nms_threshold
    for i in idx:
        keep = all(_jaccard(boxes[i], boxes[j]) <= adaptive_threshold
                   for j in kept)
        if keep:
            kept.append(int(i))
            if nms_eta < 1.0 and adaptive_threshold > 0.5:
                adaptive_threshold *= nms_eta
    return kept


@register_op("multiclass_nms", no_gradient=True, host=True)
def multiclass_nms_lower(ctx):
    """Reference multiclass_nms_op.cc; output [No, 6] rows
    [label, score, xmin, ymin, xmax, ymax] with per-image LoD."""
    bboxes = np.asarray(ctx.input("BBoxes"))     # [N, M, 4]
    scores = np.asarray(ctx.input("Scores"))     # [N, C, M]
    background = ctx.attr("background_label", 0)
    score_threshold = float(ctx.attr("score_threshold", 0.01))
    nms_top_k = int(ctx.attr("nms_top_k", 400))
    keep_top_k = int(ctx.attr("keep_top_k", 200))
    nms_threshold = float(ctx.attr("nms_threshold", 0.3))
    nms_eta = float(ctx.attr("nms_eta", 1.0))
    n, c, m = scores.shape
    all_rows = []
    lod = [0]
    for i in range(n):
        dets = []  # (label, score, box)
        for cls in range(c):
            if cls == background:
                continue
            kept = _nms_one_class(bboxes[i], scores[i, cls], score_threshold,
                                  nms_top_k, nms_threshold, nms_eta)
            dets.extend((cls, float(scores[i, cls, k]), bboxes[i, k])
                        for k in kept)
        if keep_top_k > -1 and len(dets) > keep_top_k:
            dets.sort(key=lambda d: -d[1])
            dets = dets[:keep_top_k]
        for label, score, box in dets:
            all_rows.append([float(label), score] + [float(v) for v in box])
        lod.append(len(all_rows))
    if not all_rows:
        out = np.full((1, 1), -1.0, np.float32)
        lod = [0] * (n + 1)
    else:
        out = np.asarray(all_rows, np.float32)
    ctx.set_output("Out", jnp.asarray(out))
    ctx.set_output_lod("Out", [lod])


# ---------------------------------------------------------------------------
# roi_pool
# ---------------------------------------------------------------------------

def _infer_roi_pool(op, block):
    x = block.var(op.input("X")[0])
    rois = block.var(op.input("ROIs")[0])
    if x.shape is None or rois.shape is None:
        raise ShapeInferenceSkip()
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    out = block.var(op.output("Out")[0])
    out.shape = (rois.shape[0], x.shape[1], ph, pw)
    out.dtype = x.dtype
    names = op.output("Argmax")
    if names:
        a = block.var(names[0])
        a.shape = out.shape
        a.dtype = "int64"


@register_op("roi_pool", infer_shape=_infer_roi_pool,
             no_grad_inputs=("ROIs",), stop_gradient_outputs=("Argmax",))
def roi_pool_lower(ctx):
    """Max pooling over ROI bins (reference roi_pool_op.h:30).  ROI bin
    membership is computed as masks over the full H×W plane so the op stays
    dense/jittable; the backward scatters grads through Argmax."""
    x = ctx.input("X")                       # [B, C, H, W]
    rois = ctx.input("ROIs")                 # [R, 5] (batch, x1, y1, x2, y2)
    ph = int(ctx.attr("pooled_height", 1))
    pw = int(ctx.attr("pooled_width", 1))
    scale = float(ctx.attr("spatial_scale", 1.0))
    b, c, h, w = x.shape

    def one_roi(roi):
        batch_id = roi[0].astype(jnp.int32)
        xs = jnp.round(roi[1].astype(jnp.float32) * scale).astype(jnp.int32)
        ys = jnp.round(roi[2].astype(jnp.float32) * scale).astype(jnp.int32)
        xe = jnp.round(roi[3].astype(jnp.float32) * scale).astype(jnp.int32)
        ye = jnp.round(roi[4].astype(jnp.float32) * scale).astype(jnp.int32)
        rh = jnp.maximum(ye - ys + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(xe - xs + 1, 1).astype(jnp.float32)
        bin_h = rh / ph
        bin_w = rw / pw
        pi = jnp.arange(ph, dtype=jnp.float32)
        pj = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.clip(jnp.floor(pi * bin_h).astype(jnp.int32) + ys, 0, h)
        hend = jnp.clip(jnp.ceil((pi + 1) * bin_h).astype(jnp.int32) + ys,
                        0, h)
        wstart = jnp.clip(jnp.floor(pj * bin_w).astype(jnp.int32) + xs, 0, w)
        wend = jnp.clip(jnp.ceil((pj + 1) * bin_w).astype(jnp.int32) + xs,
                        0, w)
        hh = jnp.arange(h)
        ww = jnp.arange(w)
        hmask = (hh[None, :] >= hstart[:, None]) & (hh[None, :] <
                                                    hend[:, None])   # [PH,H]
        wmask = (ww[None, :] >= wstart[:, None]) & (ww[None, :] <
                                                    wend[:, None])   # [PW,W]
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]  # [PH,PW,H,W]
        feat = jnp.take(x, batch_id, axis=0)                      # [C, H, W]
        vals = jnp.where(mask[None], feat[:, None, None, :, :], -jnp.inf)
        flat = vals.reshape(c, ph, pw, h * w)
        out = jnp.max(flat, axis=-1)
        arg = jnp.argmax(flat, axis=-1).astype(jnp.int64)
        empty = ~jnp.any(mask, axis=(2, 3))                       # [PH, PW]
        out = jnp.where(empty[None], 0.0, out)
        arg = jnp.where(empty[None], -1, arg)
        return out, arg, batch_id

    outs, args, batch_ids = jax.vmap(one_roi)(rois)
    ctx.set_output("Out", outs)
    ctx.set_output("Argmax", args)


@register_grad_lower("roi_pool")
def roi_pool_grad_lower(ctx):
    """Scatter-add dOut into dX at the recorded Argmax positions."""
    x = ctx.input("X")
    rois = ctx.input("ROIs")
    argmax = ctx.input("Argmax")             # [R, C, PH, PW] flat h*w or -1
    dout = ctx.input("Out@GRAD")
    gname = ctx.op.output("X@GRAD")
    if not gname or not gname[0]:
        return
    b, c, h, w = x.shape
    r = rois.shape[0]
    batch_ids = rois[:, 0].astype(jnp.int32)            # [R]
    dx = jnp.zeros((b, c, h * w), x.dtype)
    valid = argmax >= 0
    flat_arg = jnp.maximum(argmax, 0).astype(jnp.int32)  # [R, C, PH, PW]
    contrib = jnp.where(valid, dout, 0.0)
    bidx = jnp.broadcast_to(batch_ids[:, None, None, None], argmax.shape)
    cidx = jnp.broadcast_to(jnp.arange(c)[None, :, None, None], argmax.shape)
    dx = dx.at[bidx.reshape(-1), cidx.reshape(-1),
               flat_arg.reshape(-1)].add(contrib.reshape(-1))
    ctx.outputs[gname[0]] = dx.reshape(b, c, h, w)


# ---------------------------------------------------------------------------
# detection_map (host op; streaming mAP accumulators)
# ---------------------------------------------------------------------------

def _clip_box(box):
    return np.clip(box, 0.0, 1.0)


def _average_precision(tps, fps, num_pos, ap_type):
    """tps/fps: lists of (score, count) pairs; reference CalcMAP."""
    pairs_tp = sorted(tps, key=lambda p: -p[0])
    pairs_fp = sorted(fps, key=lambda p: -p[0])
    tp_sum = np.cumsum([p[1] for p in pairs_tp])
    fp_sum = np.cumsum([p[1] for p in pairs_fp])
    if len(tp_sum) == 0 or num_pos == 0:
        return None
    precision = tp_sum / np.maximum(tp_sum + fp_sum, 1e-12)
    recall = tp_sum / float(num_pos)
    if ap_type == "11point":
        max_precisions = np.zeros(11)
        start_idx = len(recall) - 1
        for j in range(10, -1, -1):
            for i in range(start_idx, -1, -1):
                if recall[i] < j / 10.0:
                    start_idx = i
                    if j > 0:
                        max_precisions[j - 1] = max_precisions[j]
                    break
                if max_precisions[j] < precision[i]:
                    max_precisions[j] = precision[i]
        return float(np.sum(max_precisions) / 11.0)
    # integral
    ap = 0.0
    prev_recall = 0.0
    for p, r in zip(precision, recall):
        if abs(r - prev_recall) > 1e-6:
            ap += p * abs(r - prev_recall)
        prev_recall = r
    return float(ap)


@register_op("detection_map", no_gradient=True, host=True)
def detection_map_lower(ctx):
    """Streaming VOC mAP (reference detection_map_op.h).  Accumulator state
    is carried as: AccumPosCount [C,1] int32; AccumTruePos / AccumFalsePos
    [K,2] float32 (score, flag) with a per-class LoD."""
    detect = np.asarray(ctx.input("DetectRes"))  # [Nd, 6]
    label = np.asarray(ctx.input("Label"))       # [Ng, 5 or 6]
    det_lod = ctx.input_lod("DetectRes")
    label_lod = ctx.input_lod("Label")
    class_num = int(ctx.attr("class_num"))
    overlap_threshold = float(ctx.attr("overlap_threshold", 0.3))
    evaluate_difficult = bool(ctx.attr("evaluate_difficult", True))
    ap_type = ctx.attr("ap_type", "integral")
    background = ctx.attr("background_label", 0)
    if det_lod is None or label_lod is None:
        raise ValueError("detection_map requires LoD on DetectRes and Label")
    det_splits = det_lod[0]
    lab_splits = label_lod[0]
    batch = len(lab_splits) - 1

    pos_count = {}
    true_pos = {i: [] for i in range(class_num)}
    false_pos = {i: [] for i in range(class_num)}

    # merge previous state
    has_state = ctx.input("HasState")
    state_on = has_state is not None and int(np.asarray(has_state).reshape(-1)[0]) != 0
    in_pos = ctx.input("PosCount")
    if in_pos is not None and state_on:
        arr = np.asarray(in_pos).reshape(-1)
        for i in range(min(class_num, arr.shape[0])):
            pos_count[i] = int(arr[i])
        for slot, store in (("TruePos", true_pos), ("FalsePos", false_pos)):
            t = ctx.input(slot)
            tl = ctx.input_lod(slot)
            if t is None or tl is None:
                continue
            t = np.asarray(t)
            sp = tl[0]
            for i in range(len(sp) - 1):
                for j in range(int(sp[i]), int(sp[i + 1])):
                    store[i].append((float(t[j, 0]), int(t[j, 1])))

    # parse boxes per image
    for n in range(batch):
        gts = {}
        for i in range(int(lab_splits[n]), int(lab_splits[n + 1])):
            row = label[i]
            if row.shape[0] == 6:
                cls, difficult, box = int(row[0]), bool(row[1]), row[2:6]
            else:
                cls, difficult, box = int(row[0]), False, row[1:5]
            gts.setdefault(cls, []).append((box, difficult))
        for cls, boxes in gts.items():
            cnt = (len(boxes) if evaluate_difficult
                   else sum(1 for _, d in boxes if not d))
            if cnt:
                pos_count[cls] = pos_count.get(cls, 0) + cnt

        dets = {}
        for i in range(int(det_splits[n]), int(det_splits[n + 1])):
            row = detect[i]
            if row.shape[0] < 6:
                continue  # the all-empty "-1" sentinel tensor
            dets.setdefault(int(row[0]), []).append(
                (float(row[1]), row[2:6]))
        for cls, preds in dets.items():
            gt_cls = gts.get(cls)
            if not gt_cls:
                for score, _ in preds:
                    true_pos[cls].append((score, 0))
                    false_pos[cls].append((score, 1))
                continue
            visited = [False] * len(gt_cls)
            preds = sorted(preds, key=lambda p: -p[0])
            for score, box in preds:
                box = _clip_box(np.asarray(box, np.float64))
                overlaps = [_jaccard(box, np.asarray(g, np.float64))
                            for g, _ in gt_cls]
                max_idx = int(np.argmax(overlaps)) if overlaps else 0
                max_overlap = overlaps[max_idx] if overlaps else -1.0
                if max_overlap > overlap_threshold:
                    difficult = gt_cls[max_idx][1]
                    if evaluate_difficult or not difficult:
                        if not visited[max_idx]:
                            true_pos[cls].append((score, 1))
                            false_pos[cls].append((score, 0))
                            visited[max_idx] = True
                        else:
                            true_pos[cls].append((score, 0))
                            false_pos[cls].append((score, 1))
                else:
                    true_pos[cls].append((score, 0))
                    false_pos[cls].append((score, 1))

    # mAP over classes with positives (background excluded)
    aps = []
    for cls, num_pos in pos_count.items():
        if cls == background or num_pos == 0 or not true_pos.get(cls):
            continue
        ap = _average_precision(true_pos[cls], false_pos[cls], num_pos,
                                ap_type)
        if ap is not None:
            aps.append(ap)
    m_ap = float(np.mean(aps)) if aps else 0.0
    ctx.set_output("MAP", jnp.asarray([m_ap], jnp.float32))

    # serialize accumulators
    pc = np.zeros((class_num, 1), np.int32)
    for cls, cnt in pos_count.items():
        if 0 <= cls < class_num:
            pc[cls, 0] = cnt
    ctx.set_output("AccumPosCount", jnp.asarray(pc))
    for slot, store in (("AccumTruePos", true_pos),
                        ("AccumFalsePos", false_pos)):
        rows, starts = [], [0]
        for i in range(class_num):
            rows.extend(store.get(i, []))
            starts.append(len(rows))
        arr = (np.asarray(rows, np.float32) if rows
               else np.zeros((0, 2), np.float32))
        ctx.set_output(slot, jnp.asarray(arr))
        ctx.set_output_lod(slot, [starts])


# ---------------------------------------------------------------------------
# scale_sub_region (reference gserver/layers/ScaleSubRegionLayer.cpp:1,
# function/ScaleSubRegionOp.cpp:22 — legacy v2 only; no fluid op exists
# upstream)
# ---------------------------------------------------------------------------

def _infer_scale_sub_region(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype


@register_op("scale_sub_region", infer_shape=_infer_scale_sub_region,
             no_grad_inputs=("Indices",))
def scale_sub_region_lower(ctx):
    """Multiply a per-sample [C,H,W] sub-region of X by attr ``value``.

    ``Indices`` is [N, 6]: one-based ranges ``(c0, c1, h0, h1, w0, w1)``,
    inclusive on both ends (the reference iterates ``c = c0-1 .. c1-1``).
    The reference's per-element CPU loop becomes a dense boolean mask from
    three broadcasted aranges — one fused select on TPU, and the backward
    (auto-vjp) is the same select applied to the cotangent.
    """
    x = ctx.input("X")                      # [N, C, H, W]
    idx = ctx.input("Indices").astype(jnp.int32)
    value = float(ctx.attr("value", 1.0))
    _, c, h, w = x.shape

    def in_range(size, lo, hi):             # [N, size]
        r = jnp.arange(size)
        return (r[None, :] >= (lo - 1)[:, None]) & \
               (r[None, :] <= (hi - 1)[:, None])

    mask = (in_range(c, idx[:, 0], idx[:, 1])[:, :, None, None]
            & in_range(h, idx[:, 2], idx[:, 3])[:, None, :, None]
            & in_range(w, idx[:, 4], idx[:, 5])[:, None, None, :])
    ctx.set_output("Out", jnp.where(mask, x * value, x))
