"""CTC + edit-distance ops (reference ``operators/warpctc_op.cc`` — which
wraps the external warp-ctc CUDA library — ``ctc_align_op.cc``,
``edit_distance_op.cc``).

TPU re-design: CTC loss is the standard alpha recursion over the padded
label lattice as a ``lax.scan`` (no external library); grads come from
jax.vjp of the same recursion.  Edit distance runs the DP at trace time on
static-lod int sequences (it is an eval metric on host data in every
reference use).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)
from paddle_tpu.ops.sequence_ops import _require_lod, _lengths

NEG = -1e30


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


def ctc_loss_single(logits, labels, blank=0):
    """Negative log-likelihood of ``labels`` under CTC for one sequence.

    logits [T, C] (unnormalized), labels [L] (no blanks)."""
    log_probs = jax.nn.log_softmax(logits)
    L = labels.shape[0]
    # extended label sequence with blanks: [blank, l1, blank, l2, ...]
    ext = jnp.full((2 * L + 1,), blank, labels.dtype)
    ext = ext.at[1::2].set(labels)
    S = ext.shape[0]

    a0 = jnp.full((S,), NEG)
    a0 = a0.at[0].set(log_probs[0, blank])
    if L > 0:
        a0 = a0.at[1].set(log_probs[0, ext[1]])

    same_as_two_back = jnp.concatenate(
        [jnp.array([True, True]), ext[2:] == ext[:-2]])

    def step(alpha, lp):
        shift1 = jnp.concatenate([jnp.full((1,), NEG), alpha[:-1]])
        shift2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
        shift2 = jnp.where(same_as_two_back, NEG, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        return merged + lp[ext], None

    alpha, _ = jax.lax.scan(step, a0, log_probs[1:])
    return -jnp.logaddexp(alpha[S - 1], alpha[S - 2] if S > 1
                          else jnp.asarray(NEG))


@register_op("warpctc", infer_shape=_infer_skip, no_grad_inputs=("Label",))
def warpctc_lower(ctx: LowerContext):
    """Logits [N_t, C] ragged over time (lod), Label [N_l, 1] ragged;
    Loss [B, 1].  Per-sequence lattices run at their static lengths."""
    logits_flat = ctx.input("Logits")
    label_flat = ctx.input("Label")
    blank = ctx.attr("blank", 0)
    norm = ctx.attr("norm_by_times", False)
    logit_lod = _require_lod(ctx, "Logits")
    label_lod = _require_lod(ctx, "Label")
    lsp = np.asarray(logit_lod[0])
    ysp = np.asarray(label_lod[0])
    losses = []
    labels_all = label_flat.reshape(-1).astype(jnp.int32)
    for b in range(len(lsp) - 1):
        logits = logits_flat[int(lsp[b]):int(lsp[b + 1])]
        labels = labels_all[int(ysp[b]):int(ysp[b + 1])]
        loss = ctc_loss_single(logits, labels, blank)
        if norm:
            loss = loss / (int(lsp[b + 1]) - int(lsp[b]))
        losses.append(loss)
    ctx.set_output("Loss", jnp.stack(losses).reshape(-1, 1))


@register_op("ctc_align", infer_shape=_infer_skip, no_gradient=True,
             host=True)
def ctc_align_lower(ctx: LowerContext):
    """Greedy CTC decode: merge repeats then drop blanks.  Output length
    is data-dependent — runs at trace time on concrete inputs (eval path,
    like the reference's CPU kernel)."""
    x = ctx.input("Input")  # [N, 1] int ids (argmax'd upstream)
    blank = ctx.attr("blank", 0)
    lod = _require_lod(ctx, "Input")
    splits = np.asarray(lod[0])
    vals = np.asarray(x).reshape(-1)
    out, new_splits = [], [0]
    for b in range(len(splits) - 1):
        seq = vals[splits[b]:splits[b + 1]]
        merged = [int(v) for i, v in enumerate(seq)
                  if (i == 0 or v != seq[i - 1]) and int(v) != blank]
        out.extend(merged)
        new_splits.append(len(out))
    ctx.set_output("Output", jnp.asarray(np.asarray(out, np.int32))
                   .reshape(-1, 1))
    ctx.set_output_lod("Output", [new_splits])


def _levenshtein(a, b):
    m, n = len(a), len(b)
    dp = np.arange(n + 1, dtype=np.float32)
    for i in range(1, m + 1):
        prev = dp.copy()
        dp[0] = i
        for j in range(1, n + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            dp[j] = min(prev[j] + 1, dp[j - 1] + 1, prev[j - 1] + cost)
    return float(dp[n])


@register_op("edit_distance", infer_shape=_infer_skip,
             no_gradient=True, host=True)
def edit_distance_lower(ctx: LowerContext):
    hyp = ctx.input("Hyps")
    ref = ctx.input("Refs")
    normalized = ctx.attr("normalized", False)
    h_lod = _require_lod(ctx, "Hyps")
    r_lod = _require_lod(ctx, "Refs")
    hs = np.asarray(h_lod[0])
    rs = np.asarray(r_lod[0])
    hv = np.asarray(hyp).reshape(-1)
    rv = np.asarray(ref).reshape(-1)
    dists = []
    for b in range(len(hs) - 1):
        a = list(hv[hs[b]:hs[b + 1]])
        bseq = list(rv[rs[b]:rs[b + 1]])
        d = _levenshtein(a, bseq)
        if normalized and len(bseq):
            d /= len(bseq)
        dists.append(d)
    ctx.set_output("Out", jnp.asarray(dists, jnp.float32).reshape(-1, 1))
    ctx.set_output("SequenceNum", jnp.asarray([len(dists)], jnp.int32))
