"""Reader-as-IR ops: the input pipeline expressed in the program
(reference ``paddle/fluid/operators/reader/`` — create_recordio_file_reader,
open_files, create_{shuffle,batch,double_buffer,multi_pass,threaded}_reader,
create_random_data_generator — and ``reader_op_registry.h``).

TPU-native execution model
--------------------------
The reference's ``read`` op runs inside the C++ interpreter loop; here the
compiled step must stay a single XLA computation, so reader ops are
**executor pre-pass ops**: before each dispatch the Executor walks the
block, (idempotently) constructs reader objects for creation ops, pops one
batch from each ``read`` op's reader on the host, and injects the arrays
into the feed set.  The jitted step then consumes them as ordinary feeds —
no host-op cliff, and the double-buffer reader's background thread overlaps
the host→device copy of batch N+1 with the compute of batch N (the purpose
of ``create_double_buffer_reader_op.cc``).
"""

from __future__ import annotations

import pickle
import queue
import threading

import numpy as np

import jax

from paddle_tpu.ops.registry import register_op, ShapeInferenceSkip

# op types handled by the executor pre-pass (and skipped by lowering)
READER_CREATE_OPS = frozenset({
    "create_recordio_file_reader", "open_files",
    "create_random_data_generator", "create_shuffle_reader",
    "create_batch_reader", "create_double_buffer_reader",
    "create_multi_pass_reader", "create_threaded_reader",
})
READER_OPS = READER_CREATE_OPS | {"read"}


class EOFException(Exception):
    """Raised by ``read`` when the reader is exhausted (reference
    ``paddle/fluid/framework/reader.h`` EOF semantics); call
    ``reader.reset()`` and re-run."""


def _split_shapes(shape_concat, ranks):
    shapes, pos = [], 0
    for r in ranks:
        shapes.append(tuple(int(d) for d in shape_concat[pos:pos + r]))
        pos += r
    return shapes


# ---------------------------------------------------------------------------
# reader objects (host-side state, stored in the Scope under the reader
# variable's name — the ReaderHolder analog)
# ---------------------------------------------------------------------------

class _ReaderBase:
    """Subclasses implement ``_next``/``_reset``; the base owns the
    pushback buffer (batches returned by the executor when a multi-step
    pull hits EOF part-way — see ``executor._run_reader_ops``)."""

    _pushback = None

    def next(self):
        if self._pushback:
            return self._pushback.pop()
        return self._next()

    def unget(self, batch):
        """Return an already-pulled batch; served (LIFO) before _next."""
        if self._pushback is None:
            self._pushback = []
        self._pushback.append(batch)

    def reset(self):
        """Rewind.  Pushed-back batches (pulled but never consumed — a
        run_steps call that hit EOF mid-pull) SURVIVE the reset and are
        served before the rewound stream, so no data is silently lost."""
        self._reset()

    def _next(self):
        raise NotImplementedError

    def _reset(self):
        raise NotImplementedError


class RecordIOReader(_ReaderBase):
    """One pickled sample tuple per record (see
    ``recordio_writer.convert_reader_to_recordio_file``)."""

    def __init__(self, filename, shapes, dtypes):
        from paddle_tpu.recordio_writer import RecordIOScanner
        self._scanner = RecordIOScanner(filename)
        self.shapes = shapes
        self.dtypes = dtypes
        self._it = iter(self._scanner)

    def _coerce(self, sample):
        out = []
        for i, item in enumerate(sample):
            dt = self.dtypes[i] if i < len(self.dtypes) else None
            arr = np.asarray(item, dtype=dt)
            if i < len(self.shapes):
                want = self.shapes[i]
                if want and all(d > 0 for d in want) and \
                        arr.shape != tuple(want):
                    arr = arr.reshape(want)
            out.append(arr)
        return tuple(out)

    def _next(self):
        rec = next(self._it)  # StopIteration -> caller maps to EOF
        return self._coerce(pickle.loads(rec))

    def _reset(self):
        self._it = iter(self._scanner)


class FilesReader(RecordIOReader):
    """Multi-file reader over the native threaded loader
    (reference ``open_files_op.cc``)."""

    def __init__(self, filenames, shapes, dtypes, thread_num=2,
                 buffer_size=64):
        self.shapes = shapes
        self.dtypes = dtypes
        self._filenames = list(filenames)
        self._thread_num = thread_num
        self._buffer_size = buffer_size
        self._loader = None
        self.reset()

    def _reset(self):
        from paddle_tpu.recordio_writer import RecordIOLoader, RecordIOScanner
        if self._loader is not None:
            self._loader.close()
        try:
            self._loader = RecordIOLoader(self._filenames,
                                          n_threads=self._thread_num,
                                          capacity=self._buffer_size)
            self._it = iter(self._loader)
        except RuntimeError:
            # no native toolchain: chain plain scanners
            def chain():
                for f in self._filenames:
                    yield from RecordIOScanner(f)
            self._loader = None
            self._it = chain()


class RandomDataGenerator(_ReaderBase):
    """reference ``create_random_data_generator_op.cc``: endless uniform
    [low, high) float batches of the declared shapes."""

    def __init__(self, shapes, low, high, seed=0):
        self.shapes = shapes
        self.low, self.high = low, high
        self._rng = np.random.RandomState(seed or None)

    def _next(self):
        return tuple(self._rng.uniform(self.low, self.high,
                                       size=s).astype("float32")
                     for s in self.shapes)

    def _reset(self):
        pass


class ShuffleReader(_ReaderBase):
    def __init__(self, underlying, buffer_size, seed=0):
        self.u = underlying
        self.buffer_size = buffer_size
        self._rng = np.random.RandomState(seed or None)
        self._buf = []

    def _fill(self):
        while len(self._buf) < self.buffer_size:
            try:
                self._buf.append(self.u.next())
            except StopIteration:
                break

    def _next(self):
        self._fill()
        if not self._buf:
            raise StopIteration
        i = self._rng.randint(len(self._buf))
        self._buf[i], self._buf[-1] = self._buf[-1], self._buf[i]
        return self._buf.pop()

    def _reset(self):
        self._buf = []
        self.u.reset()


class BatchReader(_ReaderBase):
    """Stacks ``batch_size`` samples per slot.  Deviation from the
    reference BatchReader: the trailing partial batch is DROPPED (a smaller
    final batch would be a new static shape → one extra XLA compile)."""

    def __init__(self, underlying, batch_size):
        self.u = underlying
        self.batch_size = batch_size

    def _next(self):
        samples = []
        for _ in range(self.batch_size):
            try:
                samples.append(self.u.next())
            except StopIteration:
                break
        if len(samples) < self.batch_size:
            raise StopIteration
        return tuple(np.stack([s[i] for s in samples])
                     for i in range(len(samples[0])))

    def _reset(self):
        self.u.reset()


class MultiPassReader(_ReaderBase):
    def __init__(self, underlying, pass_num):
        self.u = underlying
        self.pass_num = pass_num
        self._pass = 0

    def _next(self):
        try:
            return self.u.next()
        except StopIteration:
            self._pass += 1
            if self._pass >= self.pass_num:
                raise
            self.u.reset()
            return self.u.next()

    def _reset(self):
        self._pass = 0
        self.u.reset()


class ThreadedReader(_ReaderBase):
    """Thread-safe wrapper (reference create_threaded_reader_op.cc)."""

    def __init__(self, underlying):
        self.u = underlying
        self._lock = threading.Lock()

    def _next(self):
        with self._lock:
            return self.u.next()

    def _reset(self):
        with self._lock:
            self.u.reset()


class DoubleBufferReader(_ReaderBase):
    """Background-thread prefetch + eager host→device transfer: batch N+1
    is decoded and copied while batch N computes (reference
    ``create_double_buffer_reader_op.cc``)."""

    _SENTINEL = object()

    def __init__(self, underlying, device=None, capacity=4):
        self.u = underlying
        self.device = device
        self.capacity = capacity
        self._q = None
        self._thread = None
        self._start()

    def _start(self):
        self._q = queue.Queue(maxsize=self.capacity)
        self._stop = threading.Event()

        def worker(q, stop):
            # q/stop are LOCALS: a worker that outlives a reset can only
            # ever touch its own (abandoned) queue and stop event
            while not stop.is_set():
                try:
                    batch = self.u.next()
                except StopIteration:
                    q.put(self._SENTINEL)
                    return
                except Exception as e:  # surface errors on the consumer
                    q.put(e)
                    return
                if self.device is not None:
                    batch = tuple(jax.device_put(b, self.device)
                                  for b in batch)
                else:
                    batch = tuple(jax.numpy.asarray(b) for b in batch)
                q.put(batch)

        self._thread = threading.Thread(target=worker,
                                        args=(self._q, self._stop),
                                        daemon=True)
        self._thread.start()

    def _next(self):
        item = self._q.get()
        if item is self._SENTINEL:
            # sticky EOF: the worker exited after enqueueing one sentinel;
            # re-enqueue so a retrying caller gets EOF again, not a hang
            self._q.put(self._SENTINEL)
            raise StopIteration
        if isinstance(item, Exception):
            self._q.put(item)
            raise item
        return item

    def _reset(self):
        self._stop.set()
        try:  # drain so the worker can exit a blocked put
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # resetting the underlying reader under a live worker would
            # corrupt its stream — fail loudly instead
            raise RuntimeError(
                "double-buffer worker did not stop within 5s (blocked in "
                "underlying reader?); cannot safely reset")
        self.u.reset()
        self._start()


# ---------------------------------------------------------------------------
# builders: op desc -> reader object (executor pre-pass)
# ---------------------------------------------------------------------------

def build_reader(op, scope, device=None):
    t = op.type
    a = op.attrs

    def underlying():
        name = op.input("UnderlyingReader")[0]
        u = scope.find_var(name)
        if u is None:
            raise RuntimeError(f"underlying reader {name!r} not created")
        return u

    if t == "create_recordio_file_reader":
        shapes = _split_shapes(a.get("shape_concat", []), a.get("ranks", []))
        return RecordIOReader(a["filename"], shapes, a.get("dtypes", []))
    if t == "open_files":
        shapes = _split_shapes(a.get("shape_concat", []), a.get("ranks", []))
        return FilesReader(a["file_names"], shapes, a.get("dtypes", []),
                           a.get("thread_num", 2), a.get("buffer_size", 64))
    if t == "create_random_data_generator":
        shapes = _split_shapes(a.get("shape_concat", []), a.get("ranks", []))
        return RandomDataGenerator(shapes, a.get("min", 0.0),
                                   a.get("max", 1.0), a.get("seed", 0))
    if t == "create_shuffle_reader":
        return ShuffleReader(underlying(), a.get("buffer_size", 512),
                             a.get("seed", 0))
    if t == "create_batch_reader":
        return BatchReader(underlying(), a["batch_size"])
    if t == "create_multi_pass_reader":
        return MultiPassReader(underlying(), a.get("pass_num", 1))
    if t == "create_threaded_reader":
        return ThreadedReader(underlying())
    if t == "create_double_buffer_reader":
        return DoubleBufferReader(underlying(), device=device,
                                  capacity=a.get("capacity", 4))
    raise NotImplementedError(f"unknown reader op {t!r}")


# ---------------------------------------------------------------------------
# lowerings — no-ops: the pre-pass did the work (creation ops bind scope
# state; read outputs arrive as feeds)
# ---------------------------------------------------------------------------

def _infer_skip(op, block):
    raise ShapeInferenceSkip()


def _noop_lower(ctx):
    pass


for _t in sorted(READER_CREATE_OPS):
    register_op(_t, infer_shape=_infer_skip, no_gradient=True)(_noop_lower)


@register_op("read", infer_shape=_infer_skip, no_gradient=True)
def read_lower(ctx):
    # outputs were injected as feeds by the executor pre-pass; verify
    for n in ctx.op.output("Out"):
        if n not in ctx.env:
            raise RuntimeError(
                f"read op output {n!r} missing — the executor reader "
                f"pre-pass did not run for this block")
